"""CPU-proxy perf workloads — perf regressions provable WITHOUT the TPU.

With the live tunnel hung, a perf claim that only a hardware bench can
falsify is unfalsifiable (ROADMAP re-anchor note). These workloads run the
same code paths the real benches exercise — traced MLP train steps,
continuous-serve decode ticks, a reconcile storm on FakeCluster — on CPU
with fixed seeds, and express every phase as a RATIO to an in-run anchor
measured by the same machinery:

  - mlp_train anchors data_load / stall to the jit step's own compute
    time (a machine running everything 2x slower moves numerator and
    denominator together; a code change that slows ONLY the input
    pipeline moves the ratio);
  - reconcile_storm anchors reconcile percentiles to a calibration unit
    (the median of a fixed FakeCluster get loop — the same store lock +
    deepcopy machinery a reconcile pass runs through);
  - serve_ticks anchors per-dispatch engine time to a fixed jit matmul.

Ratios are gated against checked-in budgets (tests/golden/
prof_budgets.json; `KFTPU_UPDATE_PROF_BUDGETS=1` regenerates) with
generous multipliers, so `make test` fails on an injected 2x slowdown
while machine-speed drift passes. The test-only chaos hook
(KFTPU_PROF_CHAOS="phase:N") REPEATS the phase's deterministic work N
times — no sleeps, so the injection scales with the machine exactly like
a real regression would.

Phase medians (not means) across steps make single-GC-pause outliers
irrelevant on both the budget-regen and the gate side.
"""

from __future__ import annotations

import os
import time
from functools import partial

from kubeflow_tpu.utils.envvars import ENV_PROF_CHAOS

#: default allowed measured/budget ratio per workload (a phase fails the
#: gate when measured_rel > budget_rel * ratio + GATE_SLACK)
DEFAULT_MAX_RATIO = 1.5
#: absolute slack added to every allowance: tiny phases (stall on an idle
#: CPU) have huge relative noise but bounded absolute effect
GATE_SLACK = 0.08


def chaos_repeats(phase: str) -> int:
    """Work-repeat factor for a phase from the test-only chaos hook env
    (KFTPU_PROF_CHAOS="data_load:2,reconcile:2"). 1 = untouched."""
    raw = os.environ.get(ENV_PROF_CHAOS, "")
    for term in raw.split(","):
        name, _, factor = term.partition(":")
        if name.strip() == phase and factor:
            try:
                return max(1, int(round(float(factor))))
            except ValueError:
                continue
    return 1


def chaos_flag(phase: str) -> bool:
    """Presence test for phases whose injection is a MODE, not a work
    multiplier — KFTPU_PROF_CHAOS="scaler_freeze:1" arms the frozen
    autoscaler (the factor is ignored; listing the phase turns it on)."""
    raw = os.environ.get(ENV_PROF_CHAOS, "")
    return any(term.partition(":")[0].strip() == phase
               for term in raw.split(",") if term.strip())


def _median(values: list[float]) -> float:
    vs = sorted(values)
    return vs[len(vs) // 2] if vs else 0.0


def _best_of(fn, gated_phase: str, runs: int = 2) -> dict:
    """Run a workload `runs` times and keep the run with the LOWEST gated
    ratio — scheduler/GC noise only ever inflates a run, while a real
    regression (or the chaos hook) inflates every run, so best-of-N
    narrows the gate's noise band without blunting its teeth."""
    best = None
    for _ in range(runs):
        rec = fn()
        if rec.get("skipped"):
            return rec  # environment can't run it — no second attempt
        if best is None or rec["rel"][gated_phase] \
                < best["rel"][gated_phase]:
            best = rec
    return best


def _min_phases(fn, phases: tuple[str, ...], runs: int = 2,
                attach: dict | None = None) -> dict:
    """Per-PHASE min over `runs` runs (the mlp_train rationale applied
    across whole-workload repetitions): each timing phase lands at its
    own noise floor. Count phases are deterministic and identical across
    runs, so taking the first record for everything else is exact.
    `attach` maps a phase to top-level record keys that must travel WITH
    that phase's winning run (serve_fleet's `slo` sub-dict rides
    slo_decode_burn — the acceptance record must not show run 1's burn
    rates next to run 2's gated value)."""
    recs = [fn() for _ in range(runs)]
    best = recs[0]
    for rec in recs[1:]:
        for p in phases:
            if rec["rel"][p] < best["rel"][p]:
                best["rel"][p] = rec["rel"][p]
                if p in rec.get("phases_s", {}):
                    best["phases_s"][p] = rec["phases_s"][p]
                for key in (attach or {}).get(p, ()):
                    if key in rec:
                        best[key] = rec[key]
    return best


# ------------------------------------------------------------- mlp_train


def _mlp_step():
    """One cached jit SGD step for a fixed MLP (no mesh machinery — must
    run on every jax this repo supports; mesh-requiring proxies go
    through utils/compat.set_mesh and skip-with-reason when even the
    compat chain has no resolution). Sized so the step costs MORE than
    one host fetch: the async-input gate needs an overlap-feasible
    balance (a fetch that dwarfs compute can never be hidden)."""
    import jax
    import jax.numpy as jnp

    def loss_fn(params, x, y):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        logits = h @ params["w2"] + params["b2"]
        onehot = jax.nn.one_hot(y, 10)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))

    @jax.jit
    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
        return params, loss

    return step


_MLP_STEP = None

#: mlp_train geometry. The host fetch is deliberately matmul-DOMINATED
#: (an augmentation matrix multiply, BLAS-class like the jit step) so the
#: fetch/compute balance — which decides how much input cost the async
#: loader can hide — tracks the machine's matmul speed on BOTH sides and
#: stays comparable across machines; the memory-bound take/normalize part
#: is kept small via the pool size.
_MLP_POOL = 512
_MLP_BATCH = 384
_MLP_IN = 1024
_MLP_HIDDEN = 512


def mlp_train(steps: int = 16, batch: int = _MLP_BATCH,
              pool: int = _MLP_POOL) -> dict:
    """Fixed-seed MLP train loop traced with the REAL span names
    (train.data_load / train.step) and broken down by the REAL analytics
    engine — the cpu-proxy twin of the trainer hot loop. Two loops per
    run over the SAME fetch work:

      - the inline (sync) loop: every fetch on the step critical path —
        gates `data_load` (traced fetch vs its raw un-spanned twin, ~1.0:
        span machinery overhead, machine-invariant) and `stall`;
      - the async loop: the same fetches through train/data.AsyncLoader —
        gates `data_load_async`, the critical-path input cost REMAINING
        after the background thread hides the assembly, in the same
        raw-fetch units. This is the tightened input budget: sync pays
        ~1.0 fetch units per step, the async pipeline must stay near
        zero, and the data_load:2 chaos repeat (producer work doubled —
        now slower than the step) overflows back onto the critical path
        and fails both gates.
    """
    global _MLP_STEP
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.profiling.analytics import step_breakdown
    from kubeflow_tpu.tracing import Tracer
    from kubeflow_tpu.train.data import AsyncLoader

    if _MLP_STEP is None:
        _MLP_STEP = _mlp_step()
    rng = np.random.default_rng(0)
    base = rng.standard_normal((pool, 784)).astype(np.float32)
    mix = rng.standard_normal((784, _MLP_IN)).astype(np.float32) * 0.05
    labels = rng.integers(0, 10, size=pool).astype(np.int32)
    params = {
        "w1": jnp.asarray(
            rng.standard_normal((_MLP_IN, _MLP_HIDDEN)).astype(np.float32)
            * 0.05),
        "b1": jnp.zeros((_MLP_HIDDEN,), jnp.float32),
        "w2": jnp.asarray(
            rng.standard_normal((_MLP_HIDDEN, 10)).astype(np.float32)
            * 0.05),
        "b2": jnp.zeros((10,), jnp.float32),
    }
    repeats = chaos_repeats("data_load")
    buf = np.empty_like(base)  # reused: allocator churn is not the phase

    def fetch(i: int):
        # the deterministic host-side input-pipeline work the gate
        # watches: shuffle + whole-pool normalize (into a preallocated
        # buffer) + augmentation matmul per step. The matmul allocates
        # its (batch, in_dim) output each call — identical allocation in
        # the raw twin below, so it cancels out of the gated ratio
        x = y = None
        for _ in range(repeats):
            perm = np.random.default_rng(1000 + i).permutation(pool)
            np.take(base, perm, axis=0, out=buf)
            mu = buf.mean(axis=0)
            sd = buf.std(axis=0)
            np.subtract(buf, mu, out=buf)
            np.divide(buf, sd + 1e-6, out=buf)
            x = buf[:batch] @ mix
            y = labels[perm[:batch]]
        return x, y

    def raw_fetch_once() -> float:
        # the identical numpy kernels, UN-spanned and UN-chaosed (fixed
        # perm, repeats ignored): the data_load anchor. Numerator and
        # denominator share kernels and buffers, so machine-speed noise
        # cancels almost exactly, while the chaos repeat — and any
        # regression in the span/accounting path the traced loop runs
        # through — moves only the numerator.
        perm = np.random.default_rng(999).permutation(pool)
        t0 = time.perf_counter()
        np.take(base, perm, axis=0, out=buf)
        mu = buf.mean(axis=0)
        sd = buf.std(axis=0)
        np.subtract(buf, mu, out=buf)
        np.divide(buf, sd + 1e-6, out=buf)
        buf[:batch] @ mix
        return time.perf_counter() - t0

    # warmup outside the trace: jit compile must not pollute step 0
    wx, wy = fetch(-1)
    params, loss = _MLP_STEP(params, wx, wy)
    float(loss)
    import gc

    # two traced runs, per-phase MIN of the in-run medians: scheduler /
    # frequency noise only inflates a run, a real regression (or the
    # chaos hook) inflates both — same rationale as _best_of, applied
    # per phase so numerator and denominator are each at their floor
    runs: list[dict[str, float]] = []
    n_steps = 0
    for _ in range(2):
        tracer = Tracer(capacity=8 * steps)
        # same GC posture every run: earlier workloads' garbage otherwise
        # triggers collections inside the numpy fetch and skews data_load
        gc.collect()
        for i in range(steps):
            with tracer.span("train.data_load", seq=i):
                x, y = fetch(i)
            with tracer.span("train.step", step=i):
                params, loss = _MLP_STEP(params, x, y)
                float(loss)  # host read: the true per-step sync
        per_step = step_breakdown(tracer.snapshot())
        n_steps = len(per_step)
        rec = {
            p: _median([s[p] for s in per_step])
            for p in ("data_load", "compute", "stall")
        }
        # async loop: SAME fetch work, assembled on the loader thread —
        # through the real AsyncLoader and the real wait_s/assemble_s
        # span-attr path the trainer uses, so the analytics split
        # (data_wait/data_assemble) is exercised, not simulated
        atracer = Tracer(capacity=8 * steps)
        gc.collect()
        loader = AsyncLoader(range(steps), transform=fetch, size=2,
                             name="cpu_proxy.mlp")
        try:
            for i in range(steps):
                with atracer.span("train.data_load", seq=i) as sp:
                    x, y = next(loader)
                    st = loader.pop_stats()
                    sp.set_attribute("wait_s", st["wait_s"])
                    sp.set_attribute("assemble_s", st["assemble_s"])
                with atracer.span("train.step", step=i):
                    params, loss = _MLP_STEP(params, x, y)
                    float(loss)
        finally:
            loader.close()
        async_steps = step_breakdown(atracer.snapshot())
        rec["data_load_async"] = _median(
            [s["data_load"] for s in async_steps])
        rec["data_wait_async"] = _median(
            [s["data_wait"] for s in async_steps])
        runs.append(rec)
    data = min(r["data_load"] for r in runs)
    compute = min(r["compute"] for r in runs)
    stall = min(r["stall"] for r in runs)
    adata = min(r["data_load_async"] for r in runs)
    awaits = min(r["data_wait_async"] for r in runs)
    # the data_load anchor: min over medians-of-8 raw fetches, sampled
    # after each traced run (either window may catch interference)
    gc.collect()
    fetch_unit = min(
        _median([raw_fetch_once() for _ in range(8)]) for _ in range(3))
    return {
        "workload": "mlp_train",
        "steps": n_steps,
        "anchor": "raw_fetch/compute",
        "anchor_s": round(fetch_unit, 6),
        "phases_s": {"data_load": round(data, 6),
                     "data_load_async": round(adata, 6),
                     "compute": round(compute, 6),
                     "stall": round(stall, 6)},
        "async_data_wait_s": round(awaits, 6),
        # data_load vs the raw twin of its own kernels (ratio ~= 1 + span
        # machinery overhead, machine-invariant); the async loop's
        # critical-path remainder in the SAME units; stall vs the jit step
        "rel": {"data_load": (round(data / fetch_unit, 4)
                              if fetch_unit else 0.0),
                "data_load_async": (round(adata / fetch_unit, 4)
                                    if fetch_unit else 0.0),
                "stall": round(stall / compute, 4) if compute else 0.0},
    }


# ---------------------------------------------------------- grad_overlap


def grad_overlap(layers: int = 8, dim: int = 384, batch: int = 256,
                 steps: int = 6) -> dict:
    """Comm/compute-overlap gate (ROADMAP item 5, the `mlp_train` blind
    spot the re-anchor names): the SAME per-layer backward + per-layer
    gradient-communication work run two ways —

      - overlapped: each layer's gradient is handed to a dedicated comm
        engine the moment backward produces it, and the engine works
        while the remaining backward keeps running — the schedule the
        trainer's per-rule `with_sharding_constraint`s
        (partitioner.constrain_grads) let XLA's latency-hiding scheduler
        build on TPU, where the collective rides the ICI engine in
        parallel with the MXU. On this CPU proxy the engine is a worker
        thread driving device-1 dispatches (jax CPU executes
        concurrently across host threads — measured, same mechanism the
        AsyncLoader gate uses), and only the post-backward residual
        drain lands on the critical path (`train.comm` span);
      - serialized: the full backward completes first, then every
        layer's comm runs on the critical path — the no-overlap schedule
        (one big all-reduce after backward).

    Gated: ``overlap_ratio`` = overlapped/serialized step wall (in-run,
    machine-invariant — both sides run identical kernels in the same
    process). The chaos hook ``KFTPU_PROF_CHAOS="grad_overlap:2"``
    FORCES SERIALIZATION of the overlapped loop (the engine is joined
    after every hand-off; work unchanged, pipelining destroyed), driving
    the ratio to ~1.0 — and must fail the gate. Which gradients get a
    collective comes from a REAL Partitioner's rule-derived specs over a
    transformer-shaped param tree, so the workload consumes the same
    derivation the trainer does.
    """
    import queue
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.parallel.partitioner import (
        Partitioner,
        record_comm,
    )
    from kubeflow_tpu.profiling.analytics import step_breakdown
    from kubeflow_tpu.tracing import Tracer

    forced_serial = chaos_repeats("grad_overlap") > 1
    devs = jax.devices()
    comm_dev = devs[1 % len(devs)]
    rng = np.random.default_rng(11)
    # transformer-shaped param paths: the partitioner's logical rules
    # decide which grads are sharded (and therefore owe a collective)
    pt = Partitioner()
    paths = [f"h{i}/attn/query/kernel" for i in range(layers)]
    specs = [pt.spec_for(p, (dim, dim)) for p in paths]
    comm_layers = [i for i, s in enumerate(specs)
                   if any(a is not None for a in tuple(s))]
    Ws = [jnp.asarray(rng.standard_normal((dim, dim)).astype(np.float32)
                      * 0.05) for _ in range(layers)]
    mix = jax.device_put(
        jnp.asarray(rng.standard_normal((dim, dim)).astype(np.float32)
                    * 0.05), comm_dev)
    cot0 = jnp.asarray(rng.standard_normal((batch, dim))
                       .astype(np.float32))

    @jax.jit
    def bwd(cot, w):
        # one layer of "remaining backward": produces this layer's grad
        # and the next cotangent (a dependent chain, like real reverse-mode)
        g = cot.T @ (cot @ w)
        return jnp.tanh(cot @ w.T), g

    @jax.jit
    def comm_op(g, m):
        # the all-reduce stand-in: device-1 work proportional to the
        # gradient, off the backward's device
        return jnp.tanh(g @ m) @ m

    def comm_submit(g):
        # async hand-off to the comm device: the transfer starts now
        return comm_op(jax.device_put(g, comm_dev), mix)

    # warmup: compile + first transfers outside every timed window
    c, g = bwd(cot0, Ws[0])
    jax.block_until_ready(comm_submit(g))
    jax.block_until_ready(c)

    def run_overlapped(tracer, i):
        """Backward on the main thread; comm engine thread drains a
        queue of grads as they appear. Returns the step wall time."""
        work: queue.Queue = queue.Queue()
        done: list = []

        def engine():
            while True:
                item = work.get()
                if item is None:
                    return
                done.append(jax.block_until_ready(comm_submit(item)))

        t = threading.Thread(target=engine, name="kftpu-comm-engine",
                             daemon=True)
        t0 = time.perf_counter()
        t.start()
        with tracer.span("train.step", step=i):
            cot = cot0
            for l in range(layers):
                cot, g = bwd(cot, Ws[l])
                if l in comm_layers:
                    work.put(g)
                    if forced_serial:
                        # chaos: wait for the engine to finish THIS
                        # gradient before the next backward layer —
                        # work identical, overlap destroyed
                        while not work.empty() or len(done) < sum(
                                1 for x in comm_layers if x <= l):
                            time.sleep(0)
            jax.block_until_ready(cot)
        with tracer.span("train.comm", step=i):
            # residual: whatever the engine has not finished by the time
            # backward ends is un-overlapped comm on the critical path
            work.put(None)
            t.join()
        return time.perf_counter() - t0

    def run_serialized(tracer, i):
        t0 = time.perf_counter()
        with tracer.span("train.step", step=i):
            cot = cot0
            grads = []
            for l in range(layers):
                cot, g = bwd(cot, Ws[l])
                if l in comm_layers:
                    grads.append(g)
            jax.block_until_ready(cot)
            jax.block_until_ready(grads)
        with tracer.span("train.comm", step=i):
            for g in grads:
                jax.block_until_ready(comm_submit(g))
        return time.perf_counter() - t0

    import gc

    recs = []
    for _ in range(2):
        gc.collect()
        otr, str_ = Tracer(capacity=8 * steps), Tracer(capacity=8 * steps)
        over = _median([run_overlapped(otr, i) for i in range(steps)])
        seri = _median([run_serialized(str_, i) for i in range(steps)])
        ocomm = _median([s["comm"] for s in step_breakdown(otr.snapshot())
                         if s["comm"] > 0] or [0.0])
        scomm = _median([s["comm"] for s in step_breakdown(str_.snapshot())
                         if s["comm"] > 0] or [0.0])
        recs.append({"over": over, "serial": seri,
                     "ocomm": ocomm, "scomm": scomm})
    # per-phase min across runs (the mlp_train rationale): noise only
    # ever inflates; the chaos hook inflates BOTH runs' overlapped side
    over = min(r["over"] for r in recs)
    seri = min(r["serial"] for r in recs)
    ocomm = min(r["ocomm"] for r in recs)
    scomm = min(r["scomm"] for r in recs)
    ratio = over / seri if seri else 0.0
    record_comm(ocomm, overlap_ratio=ratio)
    return {
        "workload": "grad_overlap",
        "layers": layers,
        "comm_layers": len(comm_layers),
        "steps": steps,
        "anchor": "serialized_step",
        "anchor_s": round(seri, 6),
        "phases_s": {"step_overlapped": round(over, 6),
                     "step_serialized": round(seri, 6),
                     "comm_residual": round(ocomm, 6),
                     "comm_serialized": round(scomm, 6)},
        "rel": {
            # the gated in-run ratio: <1 means the engine genuinely hid
            # comm behind the remaining backward; forced serialization
            # (the chaos teeth) drives it to ~1
            "overlap_ratio": round(ratio, 4),
        },
    }


# ----------------------------------------------------- train_restart_warm


def train_restart_warm(batch: int = 128, features: int = 64) -> dict:
    """Restart-warm compile gate (ROADMAP item 5; the restart-recompile
    cost of 2011.03641): a COLD incarnation of the real Trainer sets up
    against an empty persistent compile cache, a gang restart is
    simulated (jax.clear_caches drops every in-memory jit/compile cache,
    exactly what a new worker process starts without), and the WARM
    incarnation must

      - perform ZERO backend compilations of the train step (the
        /jax/compilation_cache/cache_misses counter the serving AOT
        tests pin, here via utils/compile_cache.compile_counts), and
      - finish setup-to-first-step in a small fraction of the cold
        incarnation's — warm/cold is an in-run ratio of the same
        machinery on the same machine, so the budget is machine-speed
        invariant.

    Setup-to-first-step is the exact window gang-restart overhead pays
    per worker: init_state + warm_start (the train.compile phase) + the
    first optimizer step completing."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from kubeflow_tpu.utils import compat
    from kubeflow_tpu.utils import compile_cache as cc

    try:
        from kubeflow_tpu.models import MnistMLP
        from kubeflow_tpu.train import Trainer, TrainerConfig
    except ImportError as e:
        return {"workload": "train_restart_warm", "skipped": str(e),
                "rel": {}, "phases_s": {}}

    rng = np.random.default_rng(7)
    x = rng.standard_normal((batch, features)).astype(np.float32)
    y = rng.integers(0, 10, size=batch).astype(np.int32)
    cache_dir = tempfile.mkdtemp(prefix="kftpu-restart-warm-")
    # the workload owns the process-global compile-cache config only for
    # its duration — later workloads/tests must see the prior state
    saved = {
        "jax_compilation_cache_dir":
            jax.config.jax_compilation_cache_dir,
        "jax_persistent_cache_min_compile_time_secs":
            jax.config.jax_persistent_cache_min_compile_time_secs,
        "jax_persistent_cache_min_entry_size_bytes":
            jax.config.jax_persistent_cache_min_entry_size_bytes,
    }

    def incarnation() -> tuple[float, float, dict]:
        """One worker lifetime: build the trainer, warm-start the step
        executables against the shared cache, run the first step.
        Returns (init_s, compile_s, warm_start info): compile_s — the
        warm_start + first-step window — is the part of restart overhead
        the compile cache exists to erase, and what the ratio gates;
        init_s (state build, whose backend compile also rides the cache)
        is reported for the full setup picture."""
        trainer = Trainer(
            MnistMLP(hidden=(32,)),
            TrainerConfig(batch_size=batch, log_every_steps=10**9,
                          compile_cache_dir=cache_dir),
        )
        t0 = time.perf_counter()
        # same order as Trainer.fit: cache live BEFORE the first compile,
        # so the state-build program is cached/hit too (enabling later
        # would leave it unwritten in cold and a guaranteed miss in warm)
        cc.enable_persistent_cache(cache_dir)
        state = trainer.init_state(x)
        t1 = time.perf_counter()
        info = trainer.warm_start(x, y)
        state, m = trainer.train_step(state, (x, y))
        float(m["loss"])  # host read: first step actually completed
        return t1 - t0, time.perf_counter() - t1, info

    try:
        import gc

        gc.collect()
        jax.clear_caches()  # a fresh process has no in-memory caches
        with compat.set_mesh(  # probe: can this jax run the Trainer path?
                Trainer(MnistMLP(hidden=(32,)),
                        TrainerConfig(batch_size=batch)).mesh):
            pass
    except compat.MeshUnavailable as e:
        shutil.rmtree(cache_dir, ignore_errors=True)
        return {"workload": "train_restart_warm", "skipped": str(e),
                "rel": {}, "phases_s": {}}

    try:
        before = cc.compile_counts()
        cold_init, cold_s, cold_info = incarnation()
        cold_misses = (cc.compile_counts()["backend_misses_total"]
                       - before["backend_misses_total"])
        # --- simulated gang restart: in-memory caches gone, persistent
        # cache + serialized executables survive (they are the DISK the
        # jobcontroller's injected KFTPU_COMPILE_CACHE_DIR points at)
        jax.clear_caches()
        gc.collect()
        before = cc.compile_counts()
        warm_init, warm_s, warm_info = incarnation()
        warm_misses = (cc.compile_counts()["backend_misses_total"]
                       - before["backend_misses_total"])
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
        for k, v in saved.items():
            jax.config.update(k, v)
        # drop the latched cache object too — it points at the deleted
        # temp dir; the next compile re-initializes from restored config
        from jax.experimental.compilation_cache import (
            compilation_cache as jax_cc,
        )

        jax_cc.reset_cache()
    return {
        "workload": "train_restart_warm",
        "cold_s": round(cold_s, 6),
        "warm_s": round(warm_s, 6),
        "cold_init_s": round(cold_init, 6),
        "warm_init_s": round(warm_init, 6),
        "cold_compiled": cold_info.get("compiled", ""),
        "warm_reloaded": warm_info.get("reloaded", ""),
        # cold MUST count misses: it proves the miss counter and the
        # persistent cache are live, so warm's zero is a real hit rate
        # and not a dead-cache vacuity (the gate test asserts this)
        "cold_backend_compiles": cold_misses,
        "anchor": "cold_compile_phase",
        "anchor_s": round(cold_s, 6),
        "phases_s": {"warm_compile": round(warm_s, 6)},
        "rel": {
            # in-run ratio: machine-invariant by construction
            "warm_cold_ratio": round(warm_s / cold_s, 4) if cold_s else 0.0,
            # a COUNT over the WHOLE warm incarnation (state build +
            # warm_start + first step) — any backend compile is a
            # regression of the restart-warm contract (budget 0, gated
            # on the absolute slack alone)
            "warm_backend_compiles": warm_misses,
        },
    }


# ------------------------------------------------------------ serve_ticks


def serve_ticks(rows: int = 4, n_requests: int = 6, prompt_len: int = 12,
                new_tokens: int = 8) -> dict:
    """Continuous-batching decode ticks on a tiny fixed-seed GPT: the
    per-dispatch engine time (scheduling + splice + decode step) in units
    of a fixed jit matmul — the serving analogue of the step breakdown."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.gpt import GPTConfig, GPTLM
    from kubeflow_tpu.serving.continuous import ContinuousBatcher

    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, mlp_dim=128, dropout_rate=0.0,
                    max_len=prompt_len + new_tokens + 2)
    model = GPTLM(cfg)
    rng = np.random.default_rng(2)
    prompts = rng.integers(1, cfg.vocab_size,
                           size=(n_requests, prompt_len)).astype(np.int32)
    variables = jax.jit(model.init)(jax.random.PRNGKey(0),
                                    jnp.asarray(prompts[:1]))
    eng = ContinuousBatcher(model, variables, max_rows=rows,
                            default_max_new_tokens=new_tokens)
    # warmup: compile prefill + decode + splice once, outside the timing
    eng.submit(prompts[0], max_new_tokens=2)
    eng.run_until_idle()
    step0 = eng.step_count
    t0 = time.perf_counter()
    reqs = [eng.submit(p, max_new_tokens=new_tokens) for p in prompts]
    eng.run_until_idle()
    dt = time.perf_counter() - t0
    dispatches = max(eng.step_count - step0, 1)
    toks = sum(len(r.result(timeout=0)) for r in reqs if r.done.is_set())
    unit = _calibration_unit()
    per_dispatch = dt / dispatches
    return {
        "workload": "serve_ticks",
        "dispatches": dispatches,
        "tokens": toks,
        "anchor": "matmul_unit",
        "anchor_s": round(unit, 6),
        "phases_s": {"tick": round(per_dispatch, 6)},
        "rel": {"tick": round(per_dispatch / unit, 4) if unit else 0.0},
    }


_CALIBRATION_UNIT = None


def _calibration_unit() -> float:
    """Median seconds of a fixed 256x256 jit matmul + host read — the
    machine-speed normalizer for workloads without an in-run compute
    anchor. Cached per process (the gate compares one process's run)."""
    global _CALIBRATION_UNIT
    if _CALIBRATION_UNIT is not None:
        return _CALIBRATION_UNIT
    import jax
    import jax.numpy as jnp
    import numpy as np

    a = jnp.asarray(np.random.default_rng(3)
                    .standard_normal((256, 256)).astype(np.float32))
    f = jax.jit(lambda m: (m @ m).sum())
    float(f(a))  # compile
    samples = []
    for _ in range(20):
        t0 = time.perf_counter()
        float(f(a))
        samples.append(time.perf_counter() - t0)
    _CALIBRATION_UNIT = _median(samples)
    return _CALIBRATION_UNIT


# ------------------------------------------------------------ serve_fleet


def _arm_decode_chaos(engines, repeats: int) -> None:
    """KFTPU_PROF_CHAOS="decode_tick:N": repeat each engine's per-tick
    device dispatches (decode scan + prefill chunk) N times — work
    repeated, never slept, so the injection scales with the machine
    exactly like a real engine regression. The calibration anchor does
    NOT pass through these wrappers, so the gate's teeth bite."""
    if repeats <= 1:
        return
    import jax

    def wrap(fn):
        def run(*args, **kwargs):
            # pure jitted calls: same inputs, state unchanged. Each call
            # is SERIALIZED (block before the next dispatch) — XLA's CPU
            # client otherwise executes the independent duplicates on
            # idle pool threads in parallel and the injected work
            # disappears from the wall clock.
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            for _ in range(repeats - 1):
                jax.block_until_ready(fn(*args, **kwargs))
            return out
        return run

    for eng in engines:
        eng._step = wrap(eng._step)
        eng._apply_chunk = wrap(eng._apply_chunk)


#: decode-tick SLO threshold = this headroom x an IN-RUN healthy tick
#: median measured on an un-chaos-wrapped engine after warmup (the
#: mlp_train in-run-anchor trick): the untouched tree's samples sit at
#: ~1.0x the anchor, the decode_tick:2 chaos at ~2.0x, so the alert
#: FIRES under injected slowdown and stays quiet otherwise regardless
#: of machine speed (the falsifiable-teeth acceptance;
#: tests/test_prof_gate.py)
DECODE_SLO_HEADROOM = 1.4


def serve_fleet(replicas: int = 3, rows: int = 2, n_requests: int = 24,
                prompt_len: int = 12, shared_prefix: int = 8,
                new_tokens: int = 6, block: int = 4, chunk: int = 4,
                seed: int = 5) -> dict:
    """The fleet drill as a perf workload (docs/serving.md): N replica
    engines sharing one paged-KV pool behind the router, seeded open-loop
    tick-driven load with a mid-run replica kill. Everything the timed
    phase does is engine work, so arrivals/kill scheduled in TICK units
    make the TTFT-over-anchor ratio machine-speed invariant. Gated:

      - ttft_p99     p99 TTFT in calibration-matmul units (the serving
                     latency SLO, with the kill's requeue cost inside it)
      - reuse_computed_frac   computed prefill tokens / total prefill
                     positions during the load phase — a COUNT ratio; a
                     prefix-reuse regression drives it toward 1.0
      - dropped      requests lost across the replica kill — budget 0;
                     the zero-drop requeue contract, gated
      - slo_decode_burn   the decode-tick SLO's long-window burn rate
                     over the monitoring TSDB (docs/slo.md) — 0 on a
                     healthy tree (budget 0 + slack), driven to its cap
                     by the decode_tick:2 chaos, so the burn-rate
                     monitor itself has gated teeth

    The run is fully monitored: engines trace every request (the
    breakdown summary rides the record) and feed decode-tick samples to
    a TSDB whose recording sits INSIDE the gated steady window — the
    decode_tick budget passing WITH sampling live is the monitor's
    off-the-hot-path claim in falsifiable form (2011.03641).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.gpt import GPTConfig, GPTLM
    from kubeflow_tpu.monitoring import SLOConfig, SLOMonitor, TimeSeriesStore
    from kubeflow_tpu.serving.continuous import ContinuousBatcher
    from kubeflow_tpu.serving.fleet import (
        FleetRouter,
        PagedKVPool,
        make_prompts,
        run_loadtest_sync,
    )
    from kubeflow_tpu.tracing import Tracer

    repeats = chaos_repeats("decode_tick")
    window = 40  # steady-state decode ticks in the dedicated window
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, mlp_dim=128, dropout_rate=0.0,
                    max_len=prompt_len + new_tokens + window + 12)
    model = GPTLM(cfg)
    variables = jax.jit(model.init)(
        jax.random.PRNGKey(0),
        jnp.zeros((1, prompt_len), jnp.int32))
    # the SLO threshold is anchored BEFORE the run (the unit is cached
    # per process, so later rel computations reuse this same value)
    unit = _calibration_unit()
    pool = PagedKVPool(block_size=block, capacity_blocks=512)
    tracer = Tracer(capacity=8192, service="serve_fleet")
    tsdb = TimeSeriesStore(capacity_per_series=2048)
    engines = [
        ContinuousBatcher(model, variables, max_rows=rows,
                          default_max_new_tokens=new_tokens,
                          paged_kv=pool, prefill_chunk=chunk)
        for _ in range(replicas)
    ]
    router = FleetRouter(engines)
    # make_prompts' prompt_len is the BODY length; the shared prefix
    # prepends, so total = prompt_len (the configured budget)
    body_len = prompt_len - shared_prefix
    prompts = make_prompts(n_requests, seed=seed, vocab=cfg.vocab_size,
                           prompt_len=body_len,
                           shared_prefix=shared_prefix)
    # warmup OUTSIDE the timed window: compile every executable the load
    # phase dispatches (chunk prefill, decode step, splice, first-token
    # pick) on every replica — the gate measures serving, not XLA.
    # Tracing/TSDB attach AFTER it: warmup requests must pollute neither
    # the request breakdown nor the decode-tick SLO series (a warmup
    # tick carries compile time — a guaranteed false bad-sample).
    warm = make_prompts(replicas, seed=seed + 1, vocab=cfg.vocab_size,
                        prompt_len=body_len,
                        shared_prefix=shared_prefix)
    for eng, w in zip(engines, warm):
        eng.submit(w, max_new_tokens=2)
        eng.run_until_idle()
        # second pass with the SAME prompt: full pool match -> suffix-1
        # prefill — the shape a post-kill requeue dispatches (its blocks
        # are already pooled). Without this, the requeued request pays a
        # chunk-1 compile INSIDE the timed phase and owns p99.
        eng.submit(w, max_new_tokens=2)
        eng.run_until_idle()
    # in-run healthy decode anchor for the SLO threshold: fill replica
    # 0's rows and median-time UNWRAPPED full-load ticks — the chaos
    # hook arms only after this, so the threshold is immune to the
    # injection while the monitored samples are not
    eng0 = engines[0]
    for p in make_prompts(rows, seed=seed + 3, vocab=cfg.vocab_size,
                          prompt_len=body_len,
                          shared_prefix=shared_prefix):
        eng0.submit(p, max_new_tokens=24)
    for _ in range(rows * (prompt_len // chunk + 2)):
        eng0.tick()
        if not eng0._pending and all(eng0._rows):
            break
    # measure through the SAME machinery the monitored samples use (a
    # scratch TSDB on the engine's own decode-tick hook), so anchor and
    # samples are the identical quantity — a full-tick stopwatch here
    # would fold in per-tick host overhead the samples don't carry and
    # blunt the teeth
    anchor_tsdb = TimeSeriesStore()
    eng0.tsdb = anchor_tsdb
    for _ in range(12):
        eng0.tick()
    eng0.tsdb = None
    healthy_tick = _median(
        [v for _, v in anchor_tsdb.window("serving.decode_tick_s",
                                          3600.0)])
    eng0.run_until_idle()
    _arm_decode_chaos(engines, repeats)
    router.tracer = tracer
    for eng in engines:
        eng.tracer = tracer
        eng.tsdb = tsdb
    import gc

    gc.collect()

    def sample_counters(_tick, rtr):
        # the zero-drop SLO's input: the fleet failure counter becomes a
        # TSDB series once per loadtest tick (the on_tick sampling hook)
        tsdb.record("fleet.requests_failed_total",
                    rtr.metrics["requests_failed_total"])

    t0_wall = time.time()
    report = run_loadtest_sync(
        router, prompts, seed=seed, mean_gap_ticks=0.6,
        new_tokens=new_tokens, kill_at_tick=8, kill_replica=1,
        on_tick=sample_counters)
    summary = report.summary()
    # snapshot the LOAD phase's request spans before the steady-state
    # rows below add theirs: the breakdown summary states what the
    # seeded drill proved (requests traced == requests submitted)
    load_spans = tracer.snapshot()
    # the report's prefill ledger is a per-run DELTA (warmup excluded)
    computed = report.prefill_tokens_total
    reused = report.prefill_tokens_reused
    # steady-state decode window on the survivors: fill every row, let
    # the chunked admissions complete, then time `window` round-robin
    # passes of IDENTICAL decode work. The mean over identical ticks is
    # far less noisy than a p99 sample — this phase is what gives the
    # decode_tick chaos its teeth, while ttft_p99 pins the latency SLO.
    alive = [r.engine for r in router.replicas if r.alive]
    steady = [eng.submit(p, max_new_tokens=window + 8)
              for eng in alive for p in make_prompts(
                  rows, seed=seed + 2, vocab=cfg.vocab_size,
                  prompt_len=body_len, shared_prefix=shared_prefix)]
    for _ in range(rows * (prompt_len // chunk + 2)):
        for eng in alive:
            eng.tick()
        if all(not e._pending and all(e._rows) for e in alive):
            break
    gc.collect()
    t0 = time.perf_counter()
    for _ in range(window):
        for eng in alive:
            eng.tick()
    decode_tick = (time.perf_counter() - t0) / window
    for eng in alive:  # drain the window rows untimed
        eng.run_until_idle()
    assert all(h.done.is_set() for h in steady)
    ttft_p99 = summary["ttft_p99_s"]

    # ---- SLO evaluation over the TSDB the run filled (docs/slo.md):
    # the decode-tick objective's threshold is anchored in calibration
    # units (machine-invariant like the gate itself); both windows must
    # burn for the alert to fire. Whole-run long window + last-quarter
    # short window, integer-rounded so burn keys stay stable.
    import math

    from kubeflow_tpu.profiling.analytics import (
        aggregate_requests,
        request_breakdown,
    )

    now = time.time()
    span_s = float(math.ceil(now - t0_wall) + 1)
    slo_threshold = DECODE_SLO_HEADROOM * healthy_tick
    decode_slo = SLOConfig(
        "serving_decode_tick", metric="serving.decode_tick_s",
        kind="above", threshold=slo_threshold, budget=0.25,
        windows=((span_s, 1.0), (max(float(math.ceil(span_s / 4)), 1.0),
                                 1.0)))
    drop_slo = SLOConfig(
        "serving_zero_drop", metric="fleet.requests_failed_total",
        kind="increase", budget=0.0, windows=((span_s, 1.0),))
    monitor = SLOMonitor(tsdb, (decode_slo, drop_slo))
    alerts = monitor.evaluate(now=now)
    states = {s["name"]: s for s in monitor.describe()}
    burn_long = states["serving_decode_tick"]["burn_rates"][
        SLOMonitor._wkey(span_s)]
    breakdown = aggregate_requests(request_breakdown(load_spans))
    return {
        "workload": "serve_fleet",
        "replicas": replicas,
        "requests": n_requests,
        "completed": summary["completed"],
        "dropped_count": summary["dropped"],
        "requeued": summary["requeued"],
        "replica_killed": True,
        "ticks": report.ticks,
        "prefill_tokens_computed": computed,
        "prefill_tokens_reused": reused,
        "anchor": "matmul_unit",
        "anchor_s": round(unit, 6),
        "phases_s": {"ttft_p50": summary["ttft_p50_s"],
                     "ttft_p99": ttft_p99,
                     "decode_tick": round(decode_tick, 6)},
        "rel": {
            "ttft_p99": round(ttft_p99 / unit, 4) if unit else 0.0,
            "decode_tick": round(decode_tick / unit, 4) if unit else 0.0,
            # COUNT ratios — machine-invariant by construction
            "reuse_computed_frac": round(
                computed / max(computed + reused, 1), 4),
            "dropped": summary["dropped"],
            # the burn-rate row: 0.0 healthy (budget 0 + slack), driven
            # to the cap by the decode_tick chaos — the SLO monitor's
            # own gated teeth
            "slo_decode_burn": round(min(burn_long, 10.0), 4),
        },
        "slo": {
            "decode_tick": {
                "fired": states["serving_decode_tick"]["fired"],
                "burn_rates": states["serving_decode_tick"]["burn_rates"],
                "threshold_s": round(slo_threshold, 6),
                "healthy_tick_s": round(healthy_tick, 6),
                "samples": states["serving_decode_tick"]["samples"],
            },
            "zero_drop": {
                "fired": states["serving_zero_drop"]["fired"],
                "burn_rates": states["serving_zero_drop"]["burn_rates"],
            },
            "alerts": [a.slo for a in alerts],
        },
        "request_breakdown": breakdown,
        "monitor_samples": tsdb.stats()["samples_total"],
        "tokens_per_s_total": summary["tokens_per_s_total"],
    }


# ------------------------------------------------------------ serve_disagg


def serve_disagg(rows: int = 2, n_requests: int = 18,
                 long_body: int = 20, short_body: int = 4,
                 shared_prefix: int = 8, new_tokens: int = 6,
                 block: int = 4, chunk: int = 4, seed: int = 9) -> dict:
    """The disaggregated prefill/decode tier vs the mixed fleet, SAME
    long-prompt-heavy mix (docs/serving.md "Disaggregated prefill/
    decode"): two four-replica fleets serve identical seeded arrivals —
    (a) the BASELINE: 4 mixed replicas, every engine interleaving
    chunked prefill with its decode rows; (b) the DISAGG tier: 2 prefill
    replicas (chunks only, stall bound lifted via max_chunks_per_tick)
    publishing finished chains through the shared paged pool + 2 decode
    replicas adopting chains by digest and decoding from the first
    generated position. Both phases kill one decode-serving replica
    mid-run. Gated:

      - ttft_p99 / decode_tick      disagg tier, calibration-matmul
                                    units. decode_tick is the median
                                    DISPATCH time on the decode tier
                                    during the load — sampled through
                                    the same engine tsdb hook the SLO
                                    monitor reads, so the decode_tick:2
                                    chaos doubles exactly what the gate
                                    measures
      - ttft_p99_vs_fleet /         the acceptance ratios: the disagg
        decode_tick_vs_fleet        tier at or below the mixed fleet on
                                    the same mix. decode_tick_vs_fleet
                                    compares median FULL-TICK wall on
                                    decode-serving engines (the row's
                                    inter-token latency — in the mixed
                                    fleet those ticks interleave chunk
                                    work; on the decode tier they never
                                    do: long prompts never occupy a
                                    decode slot)
      - dropped                     budget 0 — zero-drop across the kill
      - requeue_scratch_frac        requeues that re-decoded from
                                    scratch / requeues: the resume-from-
                                    KV rescue must carry the kill
                                    (PR-9's baseline behavior was 1.0)

    KFTPU_PROF_CHAOS="decode_tick:2" doubles every engine's per-tick
    dispatches in BOTH phases — the absolute decode_tick/ttft rows fail
    while the vs_fleet ratios stay put — and the decode-tick SLO monitor
    watching the disagg tier must stay alert-quiet on an untouched tree
    (tests/test_prof_gate.py pins both sides).
    """
    import gc
    import math

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.gpt import GPTConfig, GPTLM
    from kubeflow_tpu.monitoring import SLOConfig, SLOMonitor, TimeSeriesStore
    from kubeflow_tpu.serving.continuous import ContinuousBatcher
    from kubeflow_tpu.serving.fleet import (
        FleetRouter,
        PagedKVPool,
        make_prompts,
        run_loadtest_sync,
    )

    repeats = chaos_repeats("decode_tick")
    long_len = shared_prefix + long_body
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, mlp_dim=128, dropout_rate=0.0,
                    max_len=long_len + new_tokens + 22)  # + anchor rows
    model = GPTLM(cfg)
    variables = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    unit = _calibration_unit()
    # the long-prompt-heavy mix: 2/3 long, 1/3 short, all sharing the
    # system prefix — identical prompts and arrival offsets per phase
    longs = make_prompts(n_requests, seed=seed, vocab=cfg.vocab_size,
                         prompt_len=long_body, shared_prefix=shared_prefix)
    shorts = make_prompts(n_requests, seed=seed + 1, vocab=cfg.vocab_size,
                          prompt_len=short_body,
                          shared_prefix=shared_prefix)
    prompts = [shorts[i] if i % 3 == 2 else longs[i]
               for i in range(n_requests)]

    def run_phase(disagg: bool):
        pool = PagedKVPool(block_size=block, capacity_blocks=1024)

        def mk(**kw):
            return ContinuousBatcher(
                model, variables, max_rows=rows,
                default_max_new_tokens=new_tokens,
                paged_kv=pool, prefill_chunk=chunk, **kw)

        if disagg:
            sampled = [mk() for _ in range(2)]
            reps = ([(f"prefill-{i}", mk(max_chunks_per_tick=rows),
                      "prefill") for i in range(2)]
                    + [(f"decode-{i}", e, "decode")
                       for i, e in enumerate(sampled)])
            kill = "decode-0"
        else:
            sampled = [mk() for _ in range(4)]
            reps = sampled
            kill = 1
        router = FleetRouter(reps)
        engines = [r.engine for r in router.replicas]
        # warmup OUTSIDE every timed window: compile each engine's chunk
        # fns (full + remainder + the pool-match suffix-1 shape), decode
        # step, splice, first-token pick, and the paged chain-append
        # extraction window — the gate measures serving, not XLA
        for eng in engines:
            for w in (longs[0], shorts[0]):
                eng.submit(w, max_new_tokens=2)
                eng.run_until_idle()
                eng.submit(w, max_new_tokens=2)
                eng.run_until_idle()
        # in-run healthy decode anchor (the serve_fleet trick): median
        # UNWRAPPED decode-tick samples on a decode-serving engine,
        # through the same tsdb hook the monitored samples use — armed
        # BEFORE the chaos wrap so the SLO threshold is injection-immune
        eng0 = sampled[0]
        for p in make_prompts(rows, seed=seed + 3, vocab=cfg.vocab_size,
                              prompt_len=long_body,
                              shared_prefix=shared_prefix):
            eng0.submit(p, max_new_tokens=new_tokens + 14)
        for _ in range(rows * (long_len // chunk + 2)):
            eng0.tick()
            if not eng0._pending and all(eng0._rows):
                break
        anchor_tsdb = TimeSeriesStore()
        eng0.tsdb = anchor_tsdb
        for _ in range(12):
            eng0.tick()
        eng0.tsdb = None
        healthy_tick = _median(
            [v for _, v in anchor_tsdb.window("serving.decode_tick_s",
                                              3600.0)])
        eng0.run_until_idle()
        _arm_decode_chaos(engines, repeats)
        tsdb = TimeSeriesStore(capacity_per_series=4096)
        for eng in sampled:
            eng.tsdb = tsdb
        # per-tick wall samples on the decode-SERVING engines: a tick
        # counts when the engine entered it with >=1 active decode row —
        # in the mixed fleet those ticks interleave chunk work (the cost
        # the disagg split removes from the decode path), on the disagg
        # decode tier they never do
        samples: list[float] = []

        def timed(eng):
            orig = eng.tick

            def run():
                busy_decode = any(
                    r is not None and s not in eng._pending
                    for s, r in enumerate(eng._rows))
                t0 = time.perf_counter()
                busy = orig()
                dt = time.perf_counter() - t0
                if busy_decode:
                    samples.append(dt)
                return busy

            return run

        for eng in sampled:
            eng.tick = timed(eng)

        def sample_counters(_tick, rtr):
            tsdb.record("fleet.requests_failed_total",
                        rtr.metrics["requests_failed_total"])

        # load-phase delta base: warmup + anchor traffic must not count
        # toward the "decode tier computed zero prompt tokens" proof
        decode_prefill0 = sum(
            r.engine.prefill_tokens_total for r in router.replicas
            if r.role == "decode")
        gc.collect()
        t0_wall = time.time()
        report = run_loadtest_sync(
            router, prompts, seed=seed, mean_gap_ticks=1.0,
            new_tokens=new_tokens, kill_at_tick=10, kill_replica=kill,
            on_tick=sample_counters)
        decode_prefill = sum(
            r.engine.prefill_tokens_total for r in router.replicas
            if r.role == "decode") - decode_prefill0
        return {
            "router": router,
            "summary": report.summary(),
            "tick_median": _median(samples),
            "dispatch_median": _median(
                [v for _, v in tsdb.window("serving.decode_tick_s",
                                           3600.0)]),
            "tsdb": tsdb,
            "healthy_tick": healthy_tick,
            "t0_wall": t0_wall,
            "decode_prefill": decode_prefill,
        }

    fleet = run_phase(disagg=False)
    gc.collect()
    dis = run_phase(disagg=True)

    # ---- SLO evaluation over the DISAGG tier's TSDB (the PR-12 monitor
    # must stay alert-quiet through the drill; the decode_tick:2 chaos
    # drives it past the in-run threshold on every window)
    now = time.time()
    span_s = float(math.ceil(now - dis["t0_wall"]) + 1)
    slo_threshold = DECODE_SLO_HEADROOM * dis["healthy_tick"]
    monitor = SLOMonitor(dis["tsdb"], (
        SLOConfig("serving_decode_tick", metric="serving.decode_tick_s",
                  kind="above", threshold=slo_threshold, budget=0.25,
                  windows=((span_s, 1.0),
                           (max(float(math.ceil(span_s / 4)), 1.0), 1.0))),
        SLOConfig("serving_zero_drop",
                  metric="fleet.requests_failed_total",
                  kind="increase", budget=0.0, windows=((span_s, 1.0),)),
    ))
    alerts = monitor.evaluate(now=now)
    states = {s["name"]: s for s in monitor.describe()}

    ds, fs = dis["summary"], fleet["summary"]
    d_router = dis["router"]
    requeued = max(ds["requeued"], 1)
    return {
        "workload": "serve_disagg",
        "replicas": 4,
        "requests": n_requests,
        "completed": ds["completed"],
        "dropped_count": ds["dropped"],
        "fleet_dropped_count": fs["dropped"],
        "requeued": ds["requeued"],
        "resumed": ds["resumed"],
        "resumed_tokens": ds["resumed_tokens"],
        "handoffs": d_router.metrics["prefill_handoffs_total"],
        "decode_tier_prefill_tokens": dis["decode_prefill"],
        "replica_killed": True,
        "anchor": "matmul_unit",
        "anchor_s": round(unit, 6),
        "phases_s": {
            "ttft_p99": ds["ttft_p99_s"],
            "decode_tick": round(dis["dispatch_median"], 6),
            "decode_tick_wall": round(dis["tick_median"], 6),
            "fleet_ttft_p99": fs["ttft_p99_s"],
            "fleet_decode_tick_wall": round(fleet["tick_median"], 6),
        },
        "rel": {
            "ttft_p99": round(ds["ttft_p99_s"] / unit, 4) if unit else 0.0,
            "decode_tick": round(dis["dispatch_median"] / unit, 4)
            if unit else 0.0,
            # the acceptance ratios: disagg at or below the mixed fleet
            # on the SAME mix — in-run, machine-invariant
            "ttft_p99_vs_fleet": round(
                ds["ttft_p99_s"] / max(fs["ttft_p99_s"], 1e-12), 4),
            "decode_tick_vs_fleet": round(
                dis["tick_median"] / max(fleet["tick_median"], 1e-12), 4),
            # COUNT rows — exact, tight-gated
            "dropped": ds["dropped"] + fs["dropped"],
            "requeue_scratch_frac": round(
                (ds["requeued"] - ds["resumed"]) / requeued, 4),
        },
        "slo": {
            "decode_tick": {
                "fired": states["serving_decode_tick"]["fired"],
                "burn_rates": states["serving_decode_tick"]["burn_rates"],
                "threshold_s": round(slo_threshold, 6),
                "healthy_tick_s": round(dis["healthy_tick"], 6),
                "samples": states["serving_decode_tick"]["samples"],
            },
            "zero_drop": {
                "fired": states["serving_zero_drop"]["fired"],
                "burn_rates": states["serving_zero_drop"]["burn_rates"],
            },
            "alerts": [a.slo for a in alerts],
        },
        "tokens_per_s_total": ds["tokens_per_s_total"],
    }


# -------------------------------------------------------------- serve_pods


def serve_pods(n_requests: int = 10, body: int = 6, shared_prefix: int = 4,
               new_tokens: int = 5, block: int = 4, kill_tick: int = 6,
               seed: int = 11, transport: str = "unix") -> dict:
    """Cross-process pod-backed replicas under a REAL kill
    (docs/serving.md "Pod-backed replicas"): one prefill + two decode
    pods, each a genuine subprocess behind the AF_UNIX wire protocol,
    serve the seeded mix — paged-KV chains crossing the process boundary
    on every handoff and decode leg — while one decode pod takes an
    os.kill SIGKILL mid-run. The router's token record + the client-side
    recovery chain must carry the kill with zero drops and at least one
    chain-resume rescue. Gated:

      - ttft_p99 / decode_tick      calibration-matmul units. decode_tick
                                    is the median CLIENT-side tick
                                    round-trip on a decode pod holding
                                    rows — one wire envelope + the
                                    worker's engine tick — so the
                                    decode_tick:N chaos (shipped to the
                                    workers in their SPEC, never read
                                    from the env) inflates exactly what
                                    the gate measures
      - dropped                     budget 0, slack-only — one lost
                                    request across the SIGKILL fails
      - kill_unrescued              0 when the kill was rescued by >= 1
                                    chain-resume requeue, 1 otherwise —
                                    an exact count row, so a drill whose
                                    kill lands on an idle pod (nothing
                                    proven) fails the gate rather than
                                    passing silently
      - requeue_scratch_frac        requeues that re-decoded from
                                    scratch / requeues — the home-pool
                                    recovery chain must make the requeue
                                    a resume, not a re-prefill
      - wire_retries                retried wire ops during the load
                                    (budget 0): KFTPU_PROF_CHAOS="wire:1"
                                    arms the seeded WireFault plan
                                    (resets, deadline delays, torn
                                    frames) on the decode clients and
                                    MUST fail this row — the teeth —
                                    while an untouched tree retries
                                    nothing

    transport="tcp" is the multi-host axis (`serve_pods_tcp` in the
    budget file): the same drill dialed over 127.0.0.1 TCP, with two
    extra COUNT rows — net_reconnects (supervisor redials after an
    established connection, budget 0) and dup_acks_refused (redelivered
    events the cumulative-ack filter dropped, budget 0). The
    KFTPU_PROF_CHAOS="net:1" teeth arm the seeded NetFault plan
    (black-holes, half-open replies, duplicate deliveries, a partition
    window) on the decode clients and MUST fail those rows while an
    untouched tree redials and refuses nothing.
    """
    import gc
    import shutil
    import signal
    import tempfile

    from kubeflow_tpu.serving.fleet import (
        FleetRouter,
        PagedKVPool,
        make_prompts,
        run_loadtest_sync,
        spawn_pod,
        wire_pod_deaths,
    )
    from kubeflow_tpu.serving.fleet.podclient import pod_metrics_snapshot

    repeats = chaos_repeats("decode_tick")
    wire_teeth = chaos_flag("wire")
    net_teeth = chaos_flag("net")
    unit = _calibration_unit()
    vocab = 256
    prompts = make_prompts(n_requests, seed=seed, vocab=vocab,
                           prompt_len=body, shared_prefix=shared_prefix)
    # worker-side warmup: SAME shapes as the load (compile keys), but
    # DIFFERENT content — warmup chains in a worker pool must not become
    # covering siblings of the handoff re-inserts
    warm = make_prompts(2, seed=seed + 7, vocab=vocab, prompt_len=body,
                        shared_prefix=shared_prefix)
    spec = {
        "model": {"vocab_size": vocab, "hidden_size": 64, "num_layers": 2,
                  "num_heads": 2, "mlp_dim": 128, "dropout_rate": 0.0,
                  "max_len": shared_prefix + body + new_tokens + 16},
        "seed": 0, "init_seed": seed, "max_rows": 2,
        "default_max_new_tokens": new_tokens, "eos_token_id": None,
        "prefill_chunk": 0,
        "pool": {"block_size": block, "capacity_blocks": 512},
        "warmup_prompts": [[int(t) for t in p] for p in warm],
        "warmup_new_tokens": new_tokens, "warmup_repeats": 1,
        "warmup_resume": True,
        "chaos_decode_repeats": repeats,
        "max_queue": 64,
    }
    # persistent XLA cache at a STABLE temp path: the three workers (and
    # every later run in the same gate session) share compiles, so cold
    # start is paid once per machine, not once per spawn. Warmup runs
    # before the load either way — the cache moves only un-gated startup
    # wall time, never the measured phases.
    spec["compile_cache_dir"] = os.path.join(
        tempfile.gettempdir(), "kftpu-prof-pods-xla-cache")
    state_dir = tempfile.mkdtemp(prefix="kftpu-serve-pods-")
    home = PagedKVPool(block_size=block, capacity_blocks=1024)
    roles = (("prefill-0", "prefill"), ("decode-0", "decode"),
             ("decode-1", "decode"))
    clients = []
    try:
        # spawn all three CONCURRENTLY (connect=False), then complete the
        # handshakes — total cold start is one worker's warmup, not three
        for name, _role in roles:
            clients.append(spawn_pod(name, spec, state_dir,
                                     home_pool=home, connect=False,
                                     transport=transport))
        for c in clients:
            c.connect()
        chaos_eng = None
        if wire_teeth or net_teeth:
            from kubeflow_tpu.chaos import ChaosEngine, FaultPlan

            # armed AFTER connect so startup handshakes never spend the
            # fault budget; decode clients only — the tick/submit path
            # the drill measures. wire:1 draws the WireFault plan (the
            # "wire" profile also carries the net draws); net:1 alone
            # draws only the NetFault plan
            profile = "wire" if wire_teeth else "net"
            chaos_eng = ChaosEngine(FaultPlan.from_seed(seed,
                                                        profile=profile))
            for c in clients[1:]:
                c.chaos = chaos_eng
        router = FleetRouter([(c.name, c, role)
                              for c, (_n, role) in zip(clients, roles)])
        wire_pod_deaths(router)
        victim = clients[1]

        # client-side decode-tick samples: the wire round-trip of a tick
        # driven while the client holds seated rows — the pod tier's
        # inter-token latency as the ROUTER experiences it
        samples: list[float] = []

        def timed(c):
            orig = c.tick

            def run():
                busy_rows = bool(c._rows)
                t0 = time.perf_counter()
                busy = orig()
                dt = time.perf_counter() - t0
                if busy_rows and not c.dead:
                    samples.append(dt)
                return busy

            return run

        for c in clients[1:]:
            c.tick = timed(c)

        killed = {"done": False}

        def on_tick(tick, _rtr):
            if not killed["done"] and tick >= kill_tick:
                killed["done"] = True
                # the real thing: SIGKILL the worker PROCESS mid-decode;
                # the client discovers it through the wire, the router
                # through on_death
                try:
                    os.kill(victim.worker_pid, signal.SIGKILL)
                except ProcessLookupError:
                    # a chaos-driven wire death (the net:1 partition
                    # exhausting the retry budget) already reaped it
                    pass

        pod_base = pod_metrics_snapshot()
        gc.collect()
        report = run_loadtest_sync(
            router, prompts, seed=seed, mean_gap_ticks=1.0,
            new_tokens=new_tokens, kill_replica=None, on_tick=on_tick)
        pod_now = pod_metrics_snapshot()
        rs = report.summary()
        wire_retries = (pod_now["wire_retries_total"]
                        - pod_base["wire_retries_total"])
        net_reconnects = (pod_now["net_reconnects_total"]
                          - pod_base["net_reconnects_total"])
        dup_acks = (pod_now["net_duplicate_acks_refused_total"]
                    - pod_base["net_duplicate_acks_refused_total"])
        requeued = max(rs["requeued"], 1)
        rescued = rs["requeued"] >= 1 and rs["resumed"] >= 1
        rec = {
            "workload": ("serve_pods_tcp" if transport == "tcp"
                         else "serve_pods"),
            "transport": transport,
            "pods": len(clients),
            "requests": n_requests,
            "completed": rs["completed"],
            "dropped_count": rs["dropped"],
            "requeued": rs["requeued"],
            "resumed": rs["resumed"],
            "resumed_tokens": rs["resumed_tokens"],
            "handoffs": router.metrics["prefill_handoffs_total"],
            "pod_kills": (pod_now["kills_total"]
                          - pod_base["kills_total"]),
            "handoff_bytes": (pod_now["handoff_bytes_total"]
                              - pod_base["handoff_bytes_total"]),
            "wire_chaos_armed": wire_teeth,
            "net_chaos_armed": net_teeth,
            "net_reconnects": net_reconnects,
            "dup_acks_refused": dup_acks,
            "replica_killed": killed["done"],
            "anchor": "matmul_unit",
            "anchor_s": round(unit, 6),
            "phases_s": {
                "ttft_p99": rs["ttft_p99_s"],
                "decode_tick": round(_median(samples), 6),
            },
            "rel": {
                "ttft_p99": round(rs["ttft_p99_s"] / unit, 4)
                if unit else 0.0,
                "decode_tick": round(_median(samples) / unit, 4)
                if unit else 0.0,
                # COUNT rows — exact, tight-gated
                "dropped": rs["dropped"],
                "kill_unrescued": 0 if rescued else 1,
                "requeue_scratch_frac": round(
                    (rs["requeued"] - rs["resumed"]) / requeued, 4),
                "wire_retries": wire_retries,
            },
            "tokens_per_s_total": rs["tokens_per_s_total"],
        }
        if transport == "tcp":
            # the multi-host rows (COUNTs, budget 0): a redial after an
            # established connection or a refused redelivery on an
            # untouched tree is a regression; the net:1 teeth inflate
            # both on command
            rec["rel"]["net_reconnects"] = net_reconnects
            rec["rel"]["dup_acks_refused"] = dup_acks
        return rec
    finally:
        for c in clients:
            try:
                c.kill(timeout_s=2.0)
            except (RuntimeError, OSError):  # teardown best-effort
                pass
        shutil.rmtree(state_dir, ignore_errors=True)


# --------------------------------------------------------------- prod_day


def prod_day() -> dict:
    """The production-day soak as the tier-1 gate workload (ROADMAP
    item 6; kubeflow_tpu/soak is the engine, docs/autoscaling.md the
    guide): diurnal waves against a FleetScaler-autoscaled fleet
    (scale-to-zero + wake-on-arrival through the cold-start path),
    training churn on a real control plane, seeded replica kills, one
    pod hang, one torn checkpoint — ONE report (build_slo_report +
    SLOMonitor.evaluate over the calibrated default_slos set). Gated:

      - ttft_p99                p99 time-to-first-token in SCHEDULER
                                TICKS (admission→first token) — the
                                machine-invariant, fleet-size-fair
                                latency unit of the tick-driven drill
      - dropped                 budget 0 EXACT across the whole day:
                                scale events, drains, kills, the hang —
                                nothing may lose a request
      - goodput_gap             1 − mean running/desired pod ratio of
                                the churn leg (a COUNT ratio)
      - restart_overhead_frac   non-running pod-ticks over total — the
                                restart-overhead budget
      - slo_burn                worst serving-SLO long-window burn from
                                THE report — ~0.1 healthy, driven past
                                its cap by KFTPU_PROF_CHAOS=
                                "scaler_freeze:1" (the scaler stops
                                reacting while the waves continue; the
                                burn-rate alert must fire AND fail the
                                gate — tests/test_prof_gate.py pins it)
    """
    from kubeflow_tpu.soak import SoakConfig, run_prod_day

    unit = _calibration_unit()
    rec = run_prod_day(SoakConfig(), frozen=chaos_flag("scaler_freeze"))
    burn = rec["slo"]["worst_serving_burn"]
    return {
        "workload": "prod_day",
        "frozen_scaler": rec["frozen"],
        "requests": rec["n_requests"],
        "completed": rec["completed"],
        "dropped_count": rec["dropped"],
        "shed_retries": rec["shed_retries"],
        "requeued": rec["requeued"],
        "resumed": rec["resumed"],
        "kills_injected": rec["kills_injected"],
        "hang_injected": rec["hang_injected"],
        "ticks": rec["ticks"],
        "replicas_peak": rec["replicas_peak"],
        "scaler": rec["scaler"],
        "scale_to_zero_reached": rec["scale_to_zero_reached"],
        "recovered_from_zero": rec["recovered_from_zero"],
        "ckpt_fallback_ok": rec["ckpt"].get("fallback_ok", False),
        "churn": rec["churn"],
        "slo": rec["slo"],
        "report_requests": rec["report"]["requests"],
        "ttft_threshold_ticks": rec["ttft_threshold_ticks"],
        "ttft_bad_frac": rec["ttft_bad_frac"],
        "anchor": "scheduler_tick",
        "anchor_s": round(unit, 6),
        "phases_s": {"ttft_p99_wall": rec["ttft_p99_s"],
                     "decode_tick": rec["decode_tick_s"]},
        "rel": {
            "ttft_p99": rec["ttft_p99_ticks"],
            "dropped": rec["dropped"],
            "goodput_gap": round(1.0 - rec["churn"]["goodput_mean"], 4),
            "restart_overhead_frac":
                rec["churn"]["restart_overhead_frac"],
            "slo_burn": round(min(burn, 10.0), 4),
        },
    }


# --------------------------------------------------------- diurnal_storm


def diurnal_storm() -> dict:
    """The chip-constrained day as the tier-1 scheduler gate (ROADMAP
    item 3; kubeflow_tpu/scheduler is the subsystem, docs/scheduler.md
    the guide): the prod_day diurnal waves re-run on a cluster where
    peak serving demand CANNOT fit without preempting batch training —
    two real JAXJob gangs bound through the shared ChipScheduler
    ledger, the FleetScaler's peak scale-up evicting the youngest/
    borrowing gang via the gang-restart path, the trough handing the
    chips back and the gang resuming. Gated:

      - ttft_p99             p99 TTFT in SCHEDULER TICKS — preemption
                             must keep serving latency flat (healthy
                             ~3 ticks; sched_freeze pins the fleet at
                             one replica and drives it ~15x)
      - dropped              budget 0 EXACT — preemption and quota
                             denial may delay, never lose, a request
      - serving_alerts       COUNT of fired serving_* SLO alerts,
                             budget 0 EXACT: zero serving SLO
                             violations through the whole storm
      - slo_burn             worst serving-SLO long-window burn —
                             driven past its cap by KFTPU_PROF_CHAOS=
                             "sched_freeze:1" (the ledger stops
                             granting while the waves continue; the
                             burn-rate alert must fire AND fail the
                             gate — tests/test_prof_gate.py pins it)
      - preempt_to_resume    mean eviction→re-bound latency of the
                             preempted gang in TICKS (the tick loop
                             nudges admission, so this counts how long
                             serving actually held the chips)
      - goodput_gap          1 − mean bound-chips/total-gang-chips
                             ratio of the batch leg — the batch
                             goodput floor (preemption costs bounded
                             goodput, starvation fails the gate)
      - drain_overrun_frac   extra ticks past the scheduled day over
                             day_ticks — a frozen scheduler serves the
                             backlog late through one replica and
                             overruns the day wide
    """
    from kubeflow_tpu.soak import StormConfig, run_diurnal_storm

    unit = _calibration_unit()
    rec = run_diurnal_storm(StormConfig(),
                            frozen=chaos_flag("sched_freeze"))
    burn = rec["slo"]["worst_serving_burn"]
    return {
        "workload": "diurnal_storm",
        "frozen_scheduler": rec["frozen"],
        "requests": rec["n_requests"],
        "completed": rec["completed"],
        "dropped_count": rec["dropped"],
        "shed_retries": rec["shed_retries"],
        "requeued": rec["requeued"],
        "ticks": rec["ticks"],
        "day_ticks": rec["day_ticks"],
        "replicas_peak": rec["replicas_peak"],
        "capacity_chips": rec["capacity_chips"],
        "chips_per_slice": rec["chips_per_slice"],
        "scaler": rec["scaler"],
        "chip_denies": rec["chip_denies"],
        "sched": rec["sched"],
        "batch": rec["batch"],
        "slo": rec["slo"],
        "report_requests": rec["report"]["requests"],
        "ttft_threshold_ticks": rec["ttft_threshold_ticks"],
        "ttft_bad_frac": rec["ttft_bad_frac"],
        "preempt_to_resume_ticks_max":
            rec["preempt_to_resume_ticks_max"],
        "anchor": "scheduler_tick",
        "anchor_s": round(unit, 6),
        "phases_s": {"preempt_to_resume_wall":
                     (max(rec["preempt_to_resume_s"], default=0.0)),
                     "healthy_tick": rec["healthy_tick_s"]},
        "rel": {
            "ttft_p99": rec["ttft_p99_ticks"],
            "dropped": rec["dropped"],
            "serving_alerts": float(len(rec["slo"]["serving_alerts"])),
            "slo_burn": round(min(burn, 10.0), 4),
            "preempt_to_resume": rec["preempt_to_resume_ticks_mean"],
            "goodput_gap": round(
                1.0 - rec["batch"]["goodput_mean"], 4),
            "drain_overrun_frac": round(
                max(0, rec["ticks"] - rec["day_ticks"])
                / rec["day_ticks"], 4),
        },
    }


# -------------------------------------------------------- reconcile_storm


def reconcile_storm(n_pods: int = 200, gets_per_pass: int = 8,
                    timeout_s: float = 60.0) -> dict:
    """N-pod reconcile storm on a bare FakeCluster: one ADDED event per
    pod drives one reconcile pass through the real informer -> workqueue
    -> native-driver path, each pass doing a fixed amount of store-read
    work. Reconcile-duration percentiles come from the REAL reconcile
    spans (ControllerBase emits them) and are normalized by a calibration
    loop over the same get machinery."""
    from kubeflow_tpu.controller.base import ControllerBase
    from kubeflow_tpu.controller.fakecluster import FakeCluster, Pod
    from kubeflow_tpu.api.common import ObjectMeta
    from kubeflow_tpu.profiling.analytics import control_plane_stats
    from kubeflow_tpu.tracing import Tracer
    from kubeflow_tpu.utils.retry import poll_until

    repeats = chaos_repeats("reconcile")

    class StormController(ControllerBase):
        ERROR_EVENT_KIND = "pods"

        def kind_filter(self, etype, kind, obj):
            if kind == "pods" and obj.metadata.name.startswith("storm-"):
                return obj.key
            return None

        def resync_keys(self):
            return ()

        def reconcile(self, key):
            # read-only convergent pass: fixed get work, no write-back —
            # the storm stays exactly one pass per ADDED event
            for _ in range(repeats):
                for _ in range(gets_per_pass):
                    self.cluster.get("pods", key, copy_obj=True)
            return None

    cluster = FakeCluster()
    tracer = Tracer(capacity=8 * n_pods)
    cluster.tracer = tracer

    # calibration: the same store-lock + deepcopy path a pass runs through.
    # Collect first — garbage left by earlier workloads otherwise triggers
    # gen-0 GC passes inside the deepcopy loop and skews the unit ~40%
    import gc

    ref = Pod(metadata=ObjectMeta(name="storm-calibration"))
    cluster.create("pods", ref)

    # min over medians-of-40 blocks: transient interference (a lingering
    # thread from a previous workload, a GC pass) inflates SOME blocks;
    # a real store regression inflates all of them, so min still scales
    def store_unit_blocks(n: int) -> float:
        medians = []
        for _ in range(n):
            gc.collect()
            samples = []
            for _ in range(40):
                t0 = time.perf_counter()
                cluster.get("pods", ref.key, copy_obj=True)
                samples.append(time.perf_counter() - t0)
            medians.append(_median(samples))
        return min(medians)

    unit_before = store_unit_blocks(3)

    # one worker: the gate watches per-PASS cost, and a second worker only
    # adds store-lock contention noise to the median it is gated on
    # bulk wave lands BEFORE the controller starts: the informer's initial
    # list+watch replay delivers all N at once, so the gated median
    # measures pass cost, not creator-vs-informer lock contention (which
    # is bimodal run-to-run and would blunt the gate)
    for i in range(n_pods):
        cluster.create("pods", Pod(metadata=ObjectMeta(
            name=f"storm-{i:04d}")))
    live_wave = max(n_pods // 10, 1)
    ctrl = StormController(cluster, "storm", workers=1)
    gc.collect()  # same GC posture for the measured passes as the unit
    ctrl.start()
    try:
        poll_until(
            lambda: ctrl.metrics["reconcile_total"] >= n_pods + 1 or None,
            timeout_s=timeout_s, describe="reconcile storm drained",
        )
        # small LIVE wave, each create under a span: the published events
        # carry its context, so reconcile passes parent-link to it and
        # the watch-delivery percentiles are measured, not vacuous
        for i in range(live_wave):
            with tracer.span("storm.submit", i=i):
                cluster.create("pods", Pod(metadata=ObjectMeta(
                    name=f"storm-live-{i:04d}")))
        poll_until(
            lambda: (ctrl.metrics["reconcile_total"]
                     >= n_pods + live_wave + 1) or None,
            timeout_s=timeout_s, describe="live wave drained",
        )
    finally:
        ctrl.stop()
        cluster.tracer = None
    # re-sample after the drain: the unit wants the machine's UNLOADED
    # store speed, and either window may have caught interference
    unit = min(unit_before, store_unit_blocks(2)) * gets_per_pass
    stats = control_plane_stats(tracer.snapshot())["reconcile"]["storm"]
    return {
        "workload": "reconcile_storm",
        "passes": stats["count"],
        "pods": n_pods,
        "anchor": "store_get_unit",
        "anchor_s": round(unit, 6),
        "phases_s": {"reconcile_p50": stats["p50_s"],
                     "reconcile_p99": stats["p99_s"]},
        # only the MEDIAN is gated: a 200-sample p99 is ~the 2nd-worst
        # sample (GC/scheduler noise), reported for operators but too
        # jittery to gate `make test` on
        "rel": {
            "reconcile_p50": round(stats["p50_s"] / unit, 4) if unit else 0.0,
        },
        "reconcile_p99_units": (round(stats["p99_s"] / unit, 4)
                                if unit else 0.0),
        "watch_delay_p99_s": stats["watch_delay_p99_s"],
    }


# ----------------------------------------------------------- cplane_storm


#: ownership label of the cplane-storm controller's pods
STORM_LABEL = "kubeflow-tpu.org/cplane-storm"

#: frozen PRE-REFACTOR measurement of cplane_storm's exact scenario (10k
#: pods, 8 bystander informers, 100-pod gang restart) on the single-lock
#: store with unfiltered watch fan-out and per-pod conflict-retried status
#: writes — captured at the PR-8 base commit, recorded here so every
#: budget regen carries the before/after pair. per-pod units
#: (time-to-Running / store-get unit) is the machine-invariant number;
#: jobs/sec is the same run's absolute throughput on the capture machine.
BASELINE_SINGLE_LOCK = {
    "jobs_per_s_to_running": 697.7,
    "to_running_units_per_pod": 48.17,
    "passes_per_gang_restart": 269,
}

#: the platform's OTHER pods-watching controllers, as (name, ownership
#: label) — the fan-out the sharded watch path exists to neutralize. Each
#: bystander informer subscribes pods-with-its-label (server-side): a
#: storm of someone else's pods never reaches it. Pre-refactor, every one
#: of these received and discarded every event client-side, and at 10k
#: pods that discard work was the control-plane ceiling.
BYSTANDER_CONTROLLERS = (
    ("job", "kubeflow-tpu.org/job-name"),
    ("tensorboard", "kubeflow-tpu.org/tensorboard"),
    ("inferenceservice", "kubeflow-tpu.org/inferenceservice"),
    ("experiment", "kubeflow-tpu.org/experiment-name"),
    ("notebook", "kubeflow-tpu.org/notebook"),
    ("pvcviewer", "kubeflow-tpu.org/pvcviewer"),
    ("autoscaler", "kubeflow-tpu.org/autoscaled"),
    ("pipelinerun", "kubeflow-tpu.org/pipelinerun"),
)


def cplane_storm(n_pods: int = 10000, gang_size: int = 100,
                 workers: int = 4, timeout_s: float = 300.0) -> dict:
    """10k-pod control-plane tier (ROADMAP item 3): N pods driven to
    Running through the FULL scaled path — label-filtered watch fan-out,
    keyed worker pool, coalesced status writes — in the platform's real
    subscriber shape (one owning controller + 8 bystander informers),
    reporting jobs/sec-to-Running and reconcile passes per gang restart.

    Untraced on purpose (production posture; the 200-pod storm keeps the
    traced percentiles): this workload gates THROUGHPUT. The gated ratio
    is per-pod time-to-Running in store-get units, so the budget is
    machine-speed invariant; the absolute jobs/sec lands in the budget
    regen next to the frozen pre-refactor single-lock baseline
    (docs/perf.md "Control-plane scale-out")."""
    import threading

    from kubeflow_tpu.api.common import ObjectMeta
    from kubeflow_tpu.controller.base import ControllerBase
    from kubeflow_tpu.controller.fakecluster import (
        FakeCluster, Pod, PodPhase, WatchPoller)
    from kubeflow_tpu.controller.statusbuffer import StatusWriteBuffer
    from kubeflow_tpu.utils.retry import poll_until

    repeats = chaos_repeats("reconcile")
    cluster = FakeCluster()
    buffer = StatusWriteBuffer(cluster, kind="pods")
    marked = [0]
    marked_mu = threading.Lock()

    class StormController(ControllerBase):
        ERROR_EVENT_KIND = "pods"
        # server-side push-down: only pods carrying the storm label ever
        # reach this informer's buffer
        WATCH_SELECTORS = {"pods": {STORM_LABEL: None}}

        def kind_filter(self, etype, kind, obj):
            if kind == "pods" and STORM_LABEL in obj.metadata.labels:
                return obj.key
            return None

        def resync_keys(self):
            return ()

        def reconcile(self, key):
            pod = None
            for _ in range(repeats):
                pod = self.cluster.get("pods", key)
            if pod is None or pod.status.phase != PodPhase.PENDING:
                return None
            uid = pod.metadata.uid

            def to_running(p):
                if p.status.phase != PodPhase.PENDING:
                    return False
                p.status.phase = PodPhase.RUNNING
                p.status.node = "local-node"
                p.status.start_time = time.time()

            if buffer.write(key, uid, to_running):
                with marked_mu:
                    marked[0] += 1
            return None

    # bystander informers: the other controllers' watch loops, doing what
    # an informer does with a delivered event (resolve + map + discard).
    # With server-side selectors they receive nothing for storm pods —
    # that absence is the measured win, so they must actually be running.
    stop_bystanders = threading.Event()

    def bystander(label: str):
        wp = WatchPoller(cluster, timeout=0.1, count_error=lambda: None,
                         selectors={"pods": {label: None}})
        while not stop_bystanders.is_set():
            ev = wp.get()
            if ev is not None:
                etype, kind, obj = ev
                obj.metadata.labels.get(label)  # the controller's map step

    bystander_threads = [
        threading.Thread(target=bystander, args=(label,),
                         name=f"bystander-{name}", daemon=True)
        for name, label in BYSTANDER_CONTROLLERS
    ]

    import gc

    # calibration twin of the 200-pod storm: the same store-lock + deepcopy
    # machinery, measured as min over medians-of-40 blocks
    ref = Pod(metadata=ObjectMeta(name="calibration"))
    cluster.create("pods", ref)

    def store_unit_blocks(n: int) -> float:
        medians = []
        for _ in range(n):
            gc.collect()
            samples = []
            for _ in range(40):
                t0 = time.perf_counter()
                cluster.get("pods", ref.key, copy_obj=True)
                samples.append(time.perf_counter() - t0)
            medians.append(_median(samples))
        return min(medians)

    unit_before = store_unit_blocks(3)

    def storm_pod(i: int) -> Pod:
        return Pod(metadata=ObjectMeta(name=f"storm-{i:05d}",
                                       labels={STORM_LABEL: "1"}))

    # bulk wave BEFORE the controller starts (informer replay delivers all
    # N at once), same rationale as reconcile_storm
    for i in range(n_pods):
        cluster.create("pods", storm_pod(i))
    for t in bystander_threads:
        t.start()
    ctrl = StormController(cluster, "cplane", workers=workers)
    gc.collect()
    t0 = time.perf_counter()
    ctrl.start()
    try:
        poll_until(lambda: marked[0] >= n_pods or None,
                   timeout_s=timeout_s, describe="pods to Running")
        dt = time.perf_counter() - t0

        # gang restart: kill + recreate one gang's worth of pods (new
        # incarnations), count reconcile passes to reconverge — the
        # passes-per-restart convergence-efficiency signal. Let the
        # initial wave's MODIFIED backlog drain first or its passes
        # pollute the restart count.
        drain_deadline = time.monotonic() + timeout_s
        prev = -1
        while time.monotonic() < drain_deadline:
            cur = ctrl.metrics["reconcile_total"]
            if cur == prev and len(ctrl.wq) == 0:
                break
            prev = cur
            time.sleep(0.05)
        passes0 = ctrl.metrics["reconcile_total"]
        for i in range(gang_size):
            cluster.delete("pods", f"default/storm-{i:05d}")
        for i in range(gang_size):
            cluster.create("pods", storm_pod(i))
        poll_until(lambda: marked[0] >= n_pods + gang_size or None,
                   timeout_s=timeout_s, describe="gang restart reconverged")
        restart_passes = ctrl.metrics["reconcile_total"] - passes0
    finally:
        stop_bystanders.set()
        ctrl.stop()
        buffer.close()
    unit = min(unit_before, store_unit_blocks(2))
    per_pod = dt / n_pods
    return {
        "workload": "cplane_storm",
        "pods": n_pods,
        "workers": workers,
        "bystanders": len(BYSTANDER_CONTROLLERS),
        "seconds_to_running": round(dt, 3),
        "jobs_per_s_to_running": round(n_pods / dt, 1),
        "passes_per_gang_restart": restart_passes,
        "coalesced_writes": buffer.metrics["coalesced_writes_total"],
        "flushes": buffer.metrics["flushes_total"],
        "shard_lock_waits": sum(cluster.lock_wait_counts().values()),
        "anchor": "store_get_unit",
        "anchor_s": round(unit, 9),
        "phases_s": {"to_running_per_pod": round(per_pod, 9)},
        # gated: per-pod convergence cost in store-get units (machine-
        # invariant), and passes per restarted pod (a COUNT — catches
        # reconcile-amplification regressions no timing gate can)
        "rel": {
            "to_running": round(per_pod / unit, 4) if unit else 0.0,
            "passes_per_pod_restart": round(
                restart_passes / gang_size, 4),
        },
    }


# ----------------------------------------------------------------- harness

WORKLOADS = ("mlp_train", "grad_overlap", "train_restart_warm",
             "serve_ticks", "serve_fleet", "serve_disagg", "serve_pods",
             "serve_pods_tcp", "prod_day", "diurnal_storm",
             "reconcile_storm", "cplane_storm")


def run_all(only: str = "") -> list[dict]:
    """Run every workload (an exact workload name runs just that one;
    any other `only` filters by substring), best-of-2 on each
    workload's primary gated phase."""
    fns = {
        "mlp_train": mlp_train,  # per-phase min-of-2 internally
        "grad_overlap": lambda: _best_of(grad_overlap, "overlap_ratio"),
        "train_restart_warm": lambda: _best_of(train_restart_warm,
                                               "warm_cold_ratio"),
        "serve_ticks": serve_ticks,
        "serve_fleet": lambda: _min_phases(
            serve_fleet, ("ttft_p99", "decode_tick", "slo_decode_burn"),
            attach={"slo_decode_burn": ("slo",)}),
        "serve_disagg": lambda: _min_phases(
            serve_disagg, ("ttft_p99", "decode_tick",
                           "ttft_p99_vs_fleet", "decode_tick_vs_fleet"),
            attach={"decode_tick": ("slo",)}),
        "serve_pods": lambda: _min_phases(
            serve_pods, ("ttft_p99", "decode_tick")),
        "serve_pods_tcp": lambda: _min_phases(
            partial(serve_pods, transport="tcp"),
            ("ttft_p99", "decode_tick")),
        "prod_day": lambda: _min_phases(
            prod_day, ("ttft_p99", "slo_burn", "goodput_gap",
                       "restart_overhead_frac"),
            attach={"slo_burn": ("slo",),
                    "ttft_p99": ("ttft_bad_frac",)}),
        "diurnal_storm": lambda: _min_phases(
            diurnal_storm, ("ttft_p99", "slo_burn",
                            "preempt_to_resume", "goodput_gap"),
            attach={"slo_burn": ("slo",),
                    "preempt_to_resume": ("batch", "sched")}),
        "reconcile_storm": lambda: _best_of(reconcile_storm,
                                            "reconcile_p50"),
        "cplane_storm": lambda: _best_of(cplane_storm, "to_running"),
    }
    if only in fns:
        # exact workload name: run just it ("serve_pods" must not drag
        # "serve_pods_tcp" along now that transports are an axis)
        return [fns[only]()]
    return [fns[name]() for name in WORKLOADS
            if not only or only in name]


# ------------------------------------------------------------------- gate


def make_budgets(results: list[dict]) -> dict:
    """Budget-file shape from measured results (the
    KFTPU_UPDATE_PROF_BUDGETS=1 regen path)."""
    budgets: dict = {}
    for rec in results:
        if rec.get("skipped"):
            # record WHY there is no baseline: when a later environment
            # (e.g. a jax upgrade) can run the workload, the gate treats
            # this marker as "unbudgeted by circumstance, regen when you
            # can" instead of failing every untouched tree
            budgets[rec["workload"]] = {"skipped_on_regen": rec["skipped"]}
            continue
        budgets[rec["workload"]] = {
            "rel": dict(rec["rel"]),
            "max_ratio": DEFAULT_MAX_RATIO,
            # the engine tick mixes python scheduling with jit dispatch —
            # its anchor (a bare matmul) tracks it less tightly than the
            # in-run anchors, so it gets a looser multiplier. serve_fleet:
            # ttft_p99 must stay under the decode_tick:2 chaos multiplier
            # (~1.8x, the dispatch fraction of a tick) or the teeth
            # wouldn't bite; the count ratios are exact and get tight
            # multipliers (dropped gates on the +0.08 slack alone: any
            # drop is a violation).
            "ratios": ({"tick": 3.0}
                       if rec["workload"] == "serve_ticks" else
                       # slo_decode_burn: a healthy tree burns only tail
                       # noise (well under the 1.0 firing line), while
                       # the decode_tick:2 chaos pushes the majority of
                       # samples past the in-run threshold (burn >> 1) —
                       # the 2.0 ratio leaves room for healthy noise and
                       # still fails the chaos run by a wide margin
                       # decode_tick 1.4: engine dispatches are small
                       # (~1ms) and scheduler noise moves them 15-25%
                       # run to run on a busy box, while the
                       # decode_tick:2 chaos doubles them (~2x the
                       # regen baseline) — 1.4 + slack clears healthy
                       # noise and still fails the chaos run wide
                       {"ttft_p99": 1.4, "decode_tick": 1.4,
                        "reuse_computed_frac": 1.25, "dropped": 1.0,
                        "slo_decode_burn": 2.0}
                       if rec["workload"] == "serve_fleet" else
                       # serve_disagg: the vs_fleet rows are in-run
                       # ratios of two medians measured by identical
                       # machinery — tight multipliers hold them at or
                       # below the mixed-fleet shape; the count rows
                       # (dropped, scratch-requeue fraction) gate on
                       # slack alone, so one dropped request or one
                       # full re-decode past the regen baseline fails.
                       # decode_tick's absolute row gets 1.5: the disagg
                       # decode tier's dispatches are the smallest
                       # timed unit in the suite (~1.5 matmul units) and
                       # scheduler noise moves them ~30% run to run,
                       # while the decode_tick:2 chaos lands at ~2x the
                       # regen baseline — 1.5 + slack keeps the teeth
                       # biting with margin on both sides
                       {"ttft_p99": 1.4, "decode_tick": 1.5,
                        "ttft_p99_vs_fleet": 1.2,
                        "decode_tick_vs_fleet": 1.2,
                        "dropped": 1.0, "requeue_scratch_frac": 1.0}
                       if rec["workload"] == "serve_disagg" else
                       # serve_pods: the count rows (dropped,
                       # kill_unrescued, wire_retries, scratch-requeue
                       # fraction) gate on slack alone — one dropped
                       # request, an unproven kill, or a single retried
                       # wire op past the regen baseline fails (the
                       # KFTPU_PROF_CHAOS="wire:1" teeth land squarely
                       # on wire_retries, a COUNT — so the wide timing
                       # ratios below never dull the teeth). The timing
                       # rows cross FOUR schedulable entities (client +
                       # three worker processes), so the kernel's
                       # placement of workers vs the anchor matmul
                       # moves rel ~2x run-to-run where the in-process
                       # fleets move 15-25% — 2.5 + slack covers the
                       # observed cross-run envelope while a real
                       # regression (a serialization stall, a retry
                       # storm) lands 4-10x
                       # serve_pods_tcp adds the multi-host COUNT rows
                       # (net_reconnects, dup_acks_refused, both
                       # budget 0 — the net:1 teeth's landing zone);
                       # everything else mirrors serve_pods
                       {"ttft_p99": 2.5, "decode_tick": 2.5,
                        "dropped": 1.0, "kill_unrescued": 1.0,
                        "requeue_scratch_frac": 1.0,
                        "wire_retries": 1.0, "net_reconnects": 1.0,
                        "dup_acks_refused": 1.0}
                       if rec["workload"] in ("serve_pods",
                                              "serve_pods_tcp") else
                       # prod_day: ttft_p99 is a TICK COUNT from the
                       # seeded schedule (healthy ~5, frozen-scaler
                       # ~35) — 2.0 + the tick slack below clears
                       # scheduling variance while the freeze stays
                       # 3x past the allowance; dropped gates on slack
                       # alone (one lost request fails); the churn
                       # ratios are count-based; slo_burn mirrors
                       # serve_fleet's slo_decode_burn teeth (healthy
                       # ~0.1, freeze driven to the 10.0 cap)
                       {"ttft_p99": 2.0, "dropped": 1.0,
                        "goodput_gap": 2.0,
                        "restart_overhead_frac": 2.0,
                        "slo_burn": 2.0}
                       if rec["workload"] == "prod_day" else
                       # diurnal_storm: ttft_p99 and preempt_to_resume
                       # are TICK COUNTS from the seeded schedule
                       # (healthy ttft ~3, sched_freeze ~45+ with the
                       # fleet pinned at one replica; resume ~60, a
                       # whole peak-to-trough arc) — 2.0 + the tick
                       # slacks below clear scheduling wobble while
                       # the freeze stays far past the allowance;
                       # dropped and serving_alerts gate on slack
                       # alone (one lost request or ONE fired
                       # serving_* alert fails — the zero-violations
                       # acceptance); slo_burn mirrors prod_day's
                       # teeth (healthy ~0.25, freeze at the 10.0
                       # cap); goodput_gap is the batch floor (one
                       # preemption costs ~0.13 of the day — 1.5
                       # tolerates a second eviction's worth, a
                       # starved gang lands ~0.5+); drain_overrun
                       # healthy ~0 (the backlog clears in-day),
                       # frozen ~0.35 of a day late
                       {"ttft_p99": 2.0, "dropped": 1.0,
                        "serving_alerts": 1.0, "slo_burn": 2.0,
                        "preempt_to_resume": 2.0,
                        "goodput_gap": 1.5,
                        "drain_overrun_frac": 1.5}
                       if rec["workload"] == "diurnal_storm" else
                       # warm_backend_compiles is an exact COUNT with a
                       # zero budget: ONE backend compile in the warm
                       # incarnation fails the gate (slack only); the
                       # in-run warm/cold timing ratio keeps the default
                       {"warm_backend_compiles": 1.0}
                       if rec["workload"] == "train_restart_warm" else
                       # forced serialization (the chaos teeth) lands at
                       # ~1.0; the allowance must sit BELOW that or the
                       # teeth cannot bite, and above the regen budget's
                       # noise band — 1.2x + slack does both for a
                       # healthy (<0.75) overlap ratio
                       {"overlap_ratio": 1.2}
                       if rec["workload"] == "grad_overlap" else {}),
            # per-phase slack override: the default absolute slack would
            # swamp a near-zero budget (0.02*1.5 + 0.08 tolerates a 5x
            # regression of the async win) — tighten it so a partial
            # re-inlining of host input work fails, not just a blowup.
            # grad_overlap: the forced-serial chaos lands ~0.9, so the
            # allowance must stay clearly below that — the default slack
            # would close half the gap between a healthy ratio and the
            # serialized one
            "slacks": ({"data_load_async": 0.03}
                       if rec["workload"] == "mlp_train" else
                       {"overlap_ratio": 0.03}
                       if rec["workload"] == "grad_overlap" else
                       # burn tail-noise band: healthy runs land ~0.1-0.2
                       # (a few samples past the in-run threshold), the
                       # chaos runs at 3+ — the widened slack tolerates a
                       # noisy machine's tail without closing the gap
                       {"slo_decode_burn": 0.3}
                       if rec["workload"] == "serve_fleet" else
                       # prod_day slacks: ttft_p99 is a small tick
                       # count (~5) — absolute slack of a few ticks
                       # absorbs a one-tick queue wobble without
                       # closing the gap to the frozen ~35; slo_burn
                       # and the churn ratios get the serve_fleet-
                       # style noise bands
                       {"ttft_p99": 3.0, "slo_burn": 0.3,
                        "goodput_gap": 0.1,
                        "restart_overhead_frac": 0.05}
                       if rec["workload"] == "prod_day" else
                       # diurnal_storm slacks: tick-count rows get
                       # absolute tick bands (ttft ~3 healthy vs ~45
                       # frozen; resume ~60 moves with where in the
                       # wave the eviction lands — 40 ticks of slack
                       # still fails a scheduler that holds the gang
                       # past a second peak); drain_overrun healthy
                       # is ~0 so the slack IS the band (frozen
                       # ~0.35 stays well past it)
                       {"ttft_p99": 3.0, "slo_burn": 0.3,
                        "preempt_to_resume": 40.0,
                        "goodput_gap": 0.1,
                        "drain_overrun_frac": 0.15}
                       if rec["workload"] == "diurnal_storm" else {}),
        }
        if rec["workload"] == "cplane_storm":
            # the acceptance record: this tree's throughput next to the
            # frozen pre-refactor single-lock capture (ISSUE 8 asks for
            # both numbers in every regen) — informational, the gate runs
            # on the machine-invariant "rel" ratios above
            budgets["cplane_storm"]["jobs_per_s_at_regen"] = rec[
                "jobs_per_s_to_running"]
            budgets["cplane_storm"]["baseline_single_lock"] = dict(
                BASELINE_SINGLE_LOCK)
    return budgets


def check_budgets(results: list[dict], budgets: dict) -> list[str]:
    """Gate: each measured phase ratio must stay inside its budget times
    the allowed multiplier. Returns violation strings (empty = pass).
    Missing budgets are violations too — a new workload cannot silently
    run ungated."""
    violations: list[str] = []
    for rec in results:
        if rec.get("skipped"):
            continue  # environment can't run it — reported, not gated
        b = budgets.get(rec["workload"])
        if b is None:
            violations.append(
                f"{rec['workload']}: no checked-in budget "
                "(regen with KFTPU_UPDATE_PROF_BUDGETS=1)")
            continue
        if "skipped_on_regen" in b and "rel" not in b:
            # the checked-in budgets were generated on an env that could
            # not run this workload; now it CAN — there is no baseline to
            # gate against, and bricking `make test` on an env upgrade
            # would punish the wrong change. Ungated until regenerated.
            continue
        default_ratio = b.get("max_ratio", DEFAULT_MAX_RATIO)
        for phase, rel in sorted(rec["rel"].items()):
            budget_rel = b.get("rel", {}).get(phase)
            if budget_rel is None:
                violations.append(
                    f"{rec['workload']}.{phase}: no budget for phase")
                continue
            ratio = b.get("ratios", {}).get(phase, default_ratio)
            slack = b.get("slacks", {}).get(phase, GATE_SLACK)
            allowed = budget_rel * ratio + slack
            if rel > allowed:
                violations.append(
                    f"{rec['workload']}.{phase}: measured {rel:.3f} > "
                    f"allowed {allowed:.3f} "
                    f"(budget {budget_rel:.3f} x {ratio})")
    return violations
