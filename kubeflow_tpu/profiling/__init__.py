"""kubeflow_tpu.profiling — trace analytics over the flight recorder.

The answer layer on top of tracing/ (docs/profiling.md): step-time
breakdowns with an explicit stall remainder, goodput per job incarnation
with restart overhead attributed along the causal chain, control-plane
latency percentiles, golden-pinnable restart trace shapes, and the
CPU-proxy perf workloads that gate `make test` on regressions.

Surfaces: `GET /debug/profile` (apiserver), the `profile` CLI subcommand,
the `kftpu_prof_*` /metrics families (observability.py), and
`bench.py --cpu-proxy` — all reading report.build_profile, so they agree
by construction.
"""

from kubeflow_tpu.profiling.analytics import (
    PROF_BUCKETS,
    REQUEST_PHASES,
    aggregate_requests,
    aggregate_steps,
    ancestry,
    control_plane_stats,
    goodput,
    percentile,
    request_breakdown,
    request_shape,
    restart_chains,
    restart_shape,
    scaler_shape,
    step_breakdown,
)
from kubeflow_tpu.profiling.report import (
    ProfileError,
    build_profile,
    load_trace_dir,
    platform_spans,
    profile_platform,
    render_text,
)

__all__ = [
    "PROF_BUCKETS",
    "REQUEST_PHASES",
    "ProfileError",
    "aggregate_requests",
    "aggregate_steps",
    "ancestry",
    "build_profile",
    "control_plane_stats",
    "goodput",
    "load_trace_dir",
    "percentile",
    "platform_spans",
    "profile_platform",
    "render_text",
    "request_breakdown",
    "request_shape",
    "restart_chains",
    "restart_shape",
    "scaler_shape",
    "step_breakdown",
]
