"""Profile report — the ONE breakdown every surface serves.

`build_profile` turns a span list into the canonical profile dict;
`profile_platform` feeds it the platform recorder + any worker flushes in
the tracer's trace_dir. `GET /debug/profile`, the `profile` CLI
subcommand, and the `kftpu_prof_*` /metrics families all read THIS module,
so the three surfaces can never disagree about what a step cost.

`load_trace_dir` is the CLI's strict loader: unlike
tracing.export.collect_worker_traces (which skips torn files so a drill
export never fails), an operator pointing the profiler at a directory
wants to know when a file is corrupt, empty, or missing the platform
side — each such case raises ProfileError with a one-line diagnostic.
"""

from __future__ import annotations

import glob as _glob
import os

from kubeflow_tpu.profiling.analytics import (
    PLATFORM_SPAN_NAMES,
    aggregate_steps,
    control_plane_stats,
    goodput,
    restart_chains,
    step_breakdown,
)


class ProfileError(Exception):
    """A trace set the profiler cannot analyze — message is the one-line
    operator diagnostic (the CLI prints it and exits 2)."""


def build_profile(spans: list[dict], dropped: int = 0) -> dict:
    """The canonical profile dict for a span snapshot.

    `dropped` is the recorder's spans_dropped_total: a non-zero value
    means the ring evicted spans and the breakdown may under-account —
    the report says so instead of silently producing wrong attributions.
    """
    steps = step_breakdown(spans)
    return {
        "spans": len(spans),
        "dropped_spans": dropped,
        "incomplete": dropped > 0,
        "steps": aggregate_steps(steps),
        "goodput": goodput(spans, steps),
        "control_plane": control_plane_stats(spans),
        "restarts": restart_chains(spans),
    }


#: parsed worker flushes keyed by path -> ((mtime_ns, size), spans):
#: /metrics is scraped on an interval and worker trace files are
#: write-once (atexit flush), so re-parsing every file per scrape would
#: grow scrape latency with job history for no information
_WORKER_CACHE: dict[str, tuple[tuple, list[dict]]] = {}


def _cached_worker_traces(trace_dir: str) -> list[dict]:
    import json

    from kubeflow_tpu.tracing import load_chrome_trace

    spans: list[dict] = []
    for path in sorted(_glob.glob(os.path.join(trace_dir,
                                               "trace-*.json"))):
        try:
            st = os.stat(path)
            sig = (st.st_mtime_ns, st.st_size)
            hit = _WORKER_CACHE.get(path)
            if hit is None or hit[0] != sig:
                if len(_WORKER_CACHE) > 256:  # leak backstop
                    _WORKER_CACHE.clear()
                hit = (sig, load_chrome_trace(path))
                _WORKER_CACHE[path] = hit
            spans.extend(hit[1])
        except (OSError, json.JSONDecodeError):
            continue  # torn flush of a dying pod — same as export side
    return spans


def platform_spans(platform) -> tuple[list[dict], int]:
    """(spans, dropped) for a live platform: the flight-recorder snapshot
    merged with any worker flushes in the tracer's trace_dir."""
    tracer = getattr(platform, "tracer", None)
    if tracer is None or tracer.recorder is None:
        return [], 0
    spans = list(tracer.snapshot())
    if tracer.trace_dir:
        spans.extend(_cached_worker_traces(tracer.trace_dir))
    spans.sort(key=lambda s: s["ts"])
    return spans, tracer.recorder.dropped


def profile_platform(platform) -> dict:
    spans, dropped = platform_spans(platform)
    return build_profile(spans, dropped=dropped)


def load_trace_dir(trace_dir: str) -> list[dict]:
    """Strictly load every trace file in a directory: Chrome trace-event
    `*.json` (tracing.flush / export_merged_trace output) and raw span
    `*.jsonl` dumps (write_spans_jsonl, one span dict per line)."""
    import json

    from kubeflow_tpu.tracing import load_chrome_trace
    from kubeflow_tpu.tracing.export import load_spans_jsonl

    if not os.path.isdir(trace_dir):
        raise ProfileError(f"trace dir {trace_dir!r} does not exist")
    paths = sorted(_glob.glob(os.path.join(trace_dir, "*.json"))
                   + _glob.glob(os.path.join(trace_dir, "*.jsonl")))
    if not paths:
        raise ProfileError(
            f"no trace files (*.json / *.jsonl) in {trace_dir!r}")
    spans: list[dict] = []
    for path in paths:
        try:
            if path.endswith(".jsonl"):
                spans.extend(load_spans_jsonl(path))
            else:
                spans.extend(load_chrome_trace(path))
        except (OSError, json.JSONDecodeError, ValueError) as exc:
            raise ProfileError(
                f"unreadable trace file {os.path.basename(path)}: {exc}"
            ) from exc
    if not spans:
        raise ProfileError(f"trace files in {trace_dir!r} hold no spans")
    if not any(s["name"] in PLATFORM_SPAN_NAMES for s in spans):
        raise ProfileError(
            "trace dir holds only worker spans (no platform trace) — "
            "export the platform recorder too (tracing.flush(platform."
            "tracer) / export_merged_trace), or use --server against a "
            "live platform")
    spans.sort(key=lambda s: s["ts"])
    return spans


# ------------------------------------------------------------ text rendering


def _ms(v: float) -> str:
    return f"{v * 1e3:.2f}ms"


def render_text(profile: dict) -> str:
    """Operator-facing table form of a profile dict (the default
    `profile` CLI / `?format=text` rendering)."""
    lines = [f"kftpu profile — {profile['spans']} spans"]
    if profile.get("incomplete"):
        lines.append(
            f"WARNING: breakdown incomplete "
            f"({profile['dropped_spans']} spans dropped from the flight "
            "recorder — raise start_tracing(capacity=))")
    st = profile["steps"]
    lines.append(f"step-time breakdown ({st['count']} steps, "
                 f"{st['wall_s']:.3f}s wall):")
    lines.append("  phase        total_s    frac")
    for phase in ("data_load", "compute", "checkpoint", "comm", "stall"):
        lines.append(
            f"  {phase:<12} {st['phases_s'][phase]:>8.3f}  "
            f"{st['fractions'][phase] * 100:>5.1f}%")
    lines.append(
        f"  per-step: mean {_ms(st['per_step']['mean_s'])}  "
        f"p50 {_ms(st['per_step']['p50_s'])}  "
        f"p99 {_ms(st['per_step']['p99_s'])}")
    g = profile["goodput"]
    lines.append(
        f"goodput: {g['goodput']:.3f} ({g['productive_s']:.3f}s productive "
        f"/ {g['window_s']:.3f}s window, "
        f"{len(g['incarnations'])} incarnation(s), "
        f"restart overhead {g['restart_overhead_s']:.3f}s)")
    for ch in profile["restarts"]:
        lines.append(
            f"restart {ch['restart']}: {' -> '.join(ch['chain'])} "
            f"(overhead {ch['overhead_s']:.3f}s, "
            f"{'monotonic' if ch['monotonic'] else 'OUT-OF-ORDER'})")
    cp = profile["control_plane"]
    if cp["reconcile"]:
        lines.append("control plane (reconcile):")
        lines.append("  controller     passes   p50       p99       "
                     "watch_p99")
        for ctrl, r in sorted(cp["reconcile"].items()):
            lines.append(
                f"  {ctrl:<14} {r['count']:>6}   {_ms(r['p50_s']):>8}  "
                f"{_ms(r['p99_s']):>8}  {_ms(r['watch_delay_p99_s']):>8}")
    if cp.get("http"):
        h = cp["http"]
        lines.append(
            f"http.request: {h['count']} requests, p50 {_ms(h['p50_s'])}, "
            f"p99 {_ms(h['p99_s'])}")
    return "\n".join(lines) + "\n"
