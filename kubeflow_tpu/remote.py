"""RemoteClient — SDK over the platform REST API.

Reference parity: the training-operator/katib/kserve SDKs are all k8s API
clients over HTTPS (SURVEY.md §2.1 'Python SDK'); this is the same shape
against the PlatformServer, so a process that did NOT start the platform
can apply manifests, watch verdicts, read logs, and scale jobs.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import yaml


class ApiError(RuntimeError):
    def __init__(self, code: int, message: str):
        self.code = code
        super().__init__(f"HTTP {code}: {message}")


class RemoteClient:
    def __init__(self, server: str, timeout_s: float = 10.0):
        self.server = server.rstrip("/")
        self.timeout_s = timeout_s

    # -------------------------------------------------------------- plumbing

    def _request(self, method: str, path: str, body: dict | None = None):
        req = urllib.request.Request(
            f"{self.server}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                raw = r.read()
                ctype = r.headers.get("Content-Type", "")
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")
            try:
                detail = json.loads(detail).get("error", detail)
            except json.JSONDecodeError:
                pass
            raise ApiError(exc.code, detail) from exc
        if ctype.startswith("application/json"):
            return json.loads(raw)
        return raw.decode()

    # ------------------------------------------------------------------ CRUD

    def apply(self, manifest: str | dict) -> dict:
        """kubectl-apply analogue: create from a YAML manifest (text) or dict.
        The kind in the manifest picks the API group."""
        data = yaml.safe_load(manifest) if isinstance(manifest, str) else manifest
        from kubeflow_tpu.api.serde import MANIFEST_KINDS

        bucket = MANIFEST_KINDS.get(data.get("kind", ""))
        if bucket is None:
            raise ValueError(f"unknown kind {data.get('kind')!r}")
        return self._request("POST", f"/api/v1/{bucket}", data)

    def list(self, kind: str, namespace: str = "",
             label_selector: str = "") -> list[dict]:
        """List objects; optional server-side filters (kubectl parity):
        namespace, and equality selectors k=v | k==v | k!=v comma-ANDed."""
        params = {}
        if namespace:
            params["namespace"] = namespace
        if label_selector:
            params["labelSelector"] = label_selector
        qs = f"?{urllib.parse.urlencode(params)}" if params else ""
        return self._request("GET", f"/api/v1/{kind}{qs}")

    def get(self, kind: str, name: str, namespace: str = "default") -> dict:
        return self._request("GET", f"/api/v1/{kind}/{namespace}/{name}")

    def follow_job_logs(self, name: str, namespace: str = "default",
                        replica_type: str = "worker", index: int = 0,
                        timeout_s: float = 3600.0):
        """kubectl `logs -f` analogue: yields decoded chunks as the
        replica writes them, ending when the pod finishes."""
        qs = urllib.parse.urlencode({
            "replicaType": replica_type, "index": index,
            "follow": "true", "timeoutSeconds": timeout_s,
        })
        import codecs

        req = urllib.request.Request(
            f"{self.server}/api/v1/jobs/{namespace}/{name}/logs?{qs}")
        # incremental decoding: a multi-byte UTF-8 char split across
        # chunk boundaries must not decode to U+FFFD pairs
        dec = codecs.getincrementaldecoder("utf-8")(errors="replace")
        with urllib.request.urlopen(req, timeout=timeout_s + 5) as r:
            while True:
                chunk = r.read1(65536)
                if not chunk:
                    tail = dec.decode(b"", final=True)
                    if tail:
                        yield tail
                    return
                text = dec.decode(chunk)
                if text:
                    yield text

    def delete(self, kind: str, name: str, namespace: str = "default") -> dict:
        return self._request("DELETE", f"/api/v1/{kind}/{namespace}/{name}")

    def events(self, name: str, namespace: str = "default") -> list[dict]:
        return self._request("GET", f"/api/v1/events/{namespace}/{name}")

    # ------------------------------------------------------------------ jobs

    def job_logs(self, name: str, namespace: str = "default",
                 replica_type: str = "worker", index: int = 0) -> str:
        q = urllib.parse.urlencode({"replicaType": replica_type, "index": index})
        return self._request("GET", f"/api/v1/jobs/{namespace}/{name}/logs?{q}")

    def scale_job(self, name: str, replicas: int, namespace: str = "default") -> dict:
        return self._request(
            "POST", f"/api/v1/jobs/{namespace}/{name}/scale", {"replicas": replicas}
        )

    # ------------------------------------------------------------------ watch

    def watch(self, kind: str, namespace: str = "", name: str = "",
              timeout_s: float = 60.0, keepalive_s: float = 10.0,
              label_selector: str = ""):
        """NDJSON watch stream: yields {"type": ..., "object": ...} events
        (list+watch: current objects arrive first as ADDED). Terminates when
        the server-side timeout elapses.

        Deadness detection: the server guarantees at least one line per
        keepalive_s (KEEPALIVE lines, filtered out here). The socket read
        timeout is set to ~2x that budget, so a stream with NO bytes past it
        — a dropped connection, previously indistinguishable from a quiet
        one — raises TimeoutError/OSError: callers (see _wait_terminal)
        treat it as dead, close, and relist."""
        q = urllib.parse.urlencode({
            "watch": "true", "timeoutSeconds": f"{timeout_s:.0f}",
            "keepaliveSeconds": f"{keepalive_s:g}",
            **({"namespace": namespace} if namespace else {}),
            **({"name": name} if name else {}),
            # "k=v,k2" — filtered SERVER-side (the apiserver pushes it
            # into the watch hub), not client-side after transfer
            **({"labelSelector": label_selector} if label_selector else {}),
        })
        req = urllib.request.Request(f"{self.server}/api/v1/{kind}?{q}")
        quiet_budget = max(2.0 * keepalive_s + 2.0, 5.0)
        with urllib.request.urlopen(req, timeout=quiet_budget) as resp:
            for line in resp:
                if not line.strip():
                    continue
                ev = json.loads(line)
                if ev.get("type") == "KEEPALIVE":
                    continue  # liveness only — never an API event
                yield ev

    def wait_for_job(self, name: str, namespace: str = "default",
                     timeout_s: float = 600.0, poll_s: float = 0.5) -> dict:
        """Watch until the job reaches a terminal condition (falls back to
        polling if the stream drops — e.g. a server without watch support)."""

        def terminal(job: dict) -> bool:
            conds = {
                c["type"] for c in job.get("status", {}).get("conditions", [])
                if c.get("status", True)
            }
            return bool(conds & {"Succeeded", "Failed"})

        return self._wait_terminal(
            "jobs", name, namespace, timeout_s, poll_s, terminal
        )

    def train(
        self,
        name: str,
        *,
        family: str = "mnist",
        num_workers: int = 1,
        namespace: str = "default",
        device: str = "auto",
        args: list[str] | None = None,
        elastic: tuple | None = None,
        wait: bool = True,
        timeout_s: float = 3600.0,
    ) -> dict[str, float]:
        """Remote twin of TrainingClient.train(): build the examples.<family>
        JAXJob client-side, POST it over REST, ride the watch stream to a
        terminal condition, and parse final_* metrics from worker-0's log.
        The command uses the SYMBOLIC interpreter "python" and no working
        dir — the server's pod runtime resolves both server-side (this
        client's own paths may not exist there)."""
        from kubeflow_tpu.api.jobs import build_example_train_job
        from kubeflow_tpu.api.serde import job_to_dict

        job = build_example_train_job(
            name, family=family, num_workers=num_workers, namespace=namespace,
            device=device, args=args, elastic=elastic,
        )
        self.apply(job_to_dict(job))
        if not wait:
            return {}
        done = self.wait_for_job(name, namespace, timeout_s=timeout_s)
        conds = [
            c for c in done.get("status", {}).get("conditions", [])
            if c.get("status", True)
        ]
        if not any(c["type"] == "Succeeded" for c in conds):
            failed = next((c for c in conds if c["type"] == "Failed"), None)
            detail = (
                f": {failed.get('message')}" if failed and failed.get("message")
                else f": {sorted(c['type'] for c in conds)}"
            )
            raise RuntimeError(f"train job {name} failed{detail}")
        from kubeflow_tpu.train.metrics import extract_final_metrics

        return extract_final_metrics(self.job_logs(name, namespace))

    # ------------------------------------------------------------- pipelines

    def submit_pipeline_run(
        self, name: str, pipeline_spec: dict, arguments: dict | None = None,
        namespace: str = "default", cache: bool = True,
    ) -> dict:
        """Submit compiled pipeline IR as a PipelineRun (KFP create_run
        analogue, SURVEY.md §2.6 API-server row)."""
        return self.apply({
            "apiVersion": "kubeflow-tpu.org/v1",
            "kind": "PipelineRun",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "pipelineSpec": pipeline_spec,
                "arguments": arguments or {},
                "cache": cache,
            },
        })

    def wait_for_pipeline_run(
        self, name: str, namespace: str = "default",
        timeout_s: float = 600.0, poll_s: float = 0.5,
    ) -> dict:
        return self._wait_terminal(
            "pipelineruns", name, namespace, timeout_s, poll_s,
            lambda o: o.get("status", {}).get("state") in ("Succeeded", "Failed"),
        )

    def _wait_terminal(self, kind: str, name: str, namespace: str,
                       timeout_s: float, poll_s: float, terminal) -> dict:
        """Watch until `terminal(obj)`; falls back to polling if the stream
        drops or the server lacks watch support."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                for ev in self.watch(
                    kind, namespace=namespace, name=name,
                    timeout_s=min(30.0, max(deadline - time.monotonic(), 1.0)),
                ):
                    if not isinstance(ev, dict) or "type" not in ev:
                        raise OSError("watch unsupported")
                    if ev["type"] == "DELETED":
                        raise KeyError(f"{kind} {namespace}/{name} deleted")
                    if terminal(ev["object"]):
                        return ev["object"]
            except (ApiError, OSError, json.JSONDecodeError):
                obj = self.get(kind, name, namespace)
                if terminal(obj):
                    return obj
                time.sleep(poll_s)
        raise TimeoutError(
            f"{kind} {namespace}/{name} not finished in {timeout_s}s"
        )

    def wait_for_experiment(
        self, name: str, namespace: str = "default",
        timeout_s: float = 600.0, poll_s: float = 0.5,
    ) -> dict:
        return self._wait_terminal(
            "experiments", name, namespace, timeout_s, poll_s,
            lambda o: o.get("status", {}).get("condition")
            in ("Succeeded", "Failed"),
        )

    def healthz(self) -> bool:
        try:
            return bool(self._request("GET", "/healthz").get("ok"))
        except (ApiError, OSError):
            return False
