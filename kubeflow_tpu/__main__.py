from kubeflow_tpu.cli import main

raise SystemExit(main())
