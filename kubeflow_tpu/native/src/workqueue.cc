// Rate-limited delaying work queue — the reconcile engine's native core.
//
// Semantics mirror the reference's controller work queue (client-go
// workqueue, consumed by every Go operator — SURVEY.md §2.8 native ledger):
//   - Add: dedupe while queued; if the key is mid-processing, mark dirty and
//     re-queue on Done (level-triggered reconciliation).
//   - Get: blocks until an item or shutdown.
//   - AddAfter: delay heap serviced by a background thread.
//   - AddRateLimited/Forget/NumRequeues: per-key exponential backoff.
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).

#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <queue>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct DelayedItem {
  Clock::time_point when;
  std::string key;
  bool operator>(const DelayedItem& o) const { return when > o.when; }
};

class WorkQueue {
 public:
  WorkQueue(double base_delay_s, double max_delay_s)
      : base_delay_(base_delay_s), max_delay_(max_delay_s) {
    delay_thread_ = std::thread([this] { DelayLoop(); });
  }

  ~WorkQueue() {
    ShutDown();
    delay_thread_.join();
  }

  void Add(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    AddLocked(key);
    cv_.notify_one();
  }

  void AddAfter(const std::string& key, double delay_s) {
    if (delay_s <= 0) {
      Add(key);
      return;
    }
    std::lock_guard<std::mutex> lk(mu_);
    delayed_.push({Clock::now() + std::chrono::duration_cast<Clock::duration>(
                                      std::chrono::duration<double>(delay_s)),
                   key});
    delay_cv_.notify_one();
  }

  double AddRateLimited(const std::string& key) {
    double delay;
    {
      std::lock_guard<std::mutex> lk(mu_);
      int n = requeues_[key]++;
      delay = base_delay_ * std::pow(2.0, n);
      if (delay > max_delay_) delay = max_delay_;
    }
    AddAfter(key, delay);
    return delay;
  }

  void Forget(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    requeues_.erase(key);
  }

  int NumRequeues(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = requeues_.find(key);
    return it == requeues_.end() ? 0 : it->second;
  }

  // Returns false on shutdown/timeout; fills key otherwise.
  bool Get(double timeout_s, std::string* key) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [this] { return shutdown_ || !queue_.empty(); };
    if (timeout_s < 0) {
      cv_.wait(lk, pred);
    } else if (!cv_.wait_for(lk, std::chrono::duration<double>(timeout_s),
                             pred)) {
      return false;
    }
    if (queue_.empty()) return false;  // shutdown
    *key = queue_.front();
    queue_.pop_front();
    queued_.erase(*key);
    processing_.insert(*key);
    return true;
  }

  void Done(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    processing_.erase(key);
    if (dirty_.erase(key)) {
      AddLocked(key);
      cv_.notify_one();
    }
  }

  int Len() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int>(queue_.size());
  }

  void ShutDown() {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_ = true;
    cv_.notify_all();
    delay_cv_.notify_all();
  }

  bool ShuttingDown() {
    std::lock_guard<std::mutex> lk(mu_);
    return shutdown_;
  }

 private:
  void AddLocked(const std::string& key) {
    if (shutdown_) return;
    if (processing_.count(key)) {
      dirty_.insert(key);  // re-add when Done
      return;
    }
    if (queued_.insert(key).second) queue_.push_back(key);
  }

  void DelayLoop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (!shutdown_) {
      if (delayed_.empty()) {
        delay_cv_.wait(lk, [this] { return shutdown_ || !delayed_.empty(); });
        continue;
      }
      auto next = delayed_.top().when;
      if (Clock::now() >= next) {
        AddLocked(delayed_.top().key);
        delayed_.pop();
        cv_.notify_one();
      } else {
        delay_cv_.wait_until(lk, next);
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable delay_cv_;
  std::deque<std::string> queue_;
  std::set<std::string> queued_;
  std::set<std::string> processing_;
  std::set<std::string> dirty_;
  std::map<std::string, int> requeues_;
  std::priority_queue<DelayedItem, std::vector<DelayedItem>,
                      std::greater<DelayedItem>>
      delayed_;
  bool shutdown_ = false;
  double base_delay_;
  double max_delay_;
  std::thread delay_thread_;
};

}  // namespace

extern "C" {

void* kf_wq_new(double base_delay_s, double max_delay_s) {
  return new WorkQueue(base_delay_s, max_delay_s);
}
void kf_wq_free(void* q) { delete static_cast<WorkQueue*>(q); }
void kf_wq_add(void* q, const char* key) {
  static_cast<WorkQueue*>(q)->Add(key);
}
void kf_wq_add_after(void* q, const char* key, double delay_s) {
  static_cast<WorkQueue*>(q)->AddAfter(key, delay_s);
}
double kf_wq_add_rate_limited(void* q, const char* key) {
  return static_cast<WorkQueue*>(q)->AddRateLimited(key);
}
void kf_wq_forget(void* q, const char* key) {
  static_cast<WorkQueue*>(q)->Forget(key);
}
int kf_wq_num_requeues(void* q, const char* key) {
  return static_cast<WorkQueue*>(q)->NumRequeues(key);
}
// Returns a malloc'd key or nullptr; caller frees with kf_free.
char* kf_wq_get(void* q, double timeout_s) {
  std::string key;
  if (!static_cast<WorkQueue*>(q)->Get(timeout_s, &key)) return nullptr;
  return strdup(key.c_str());
}
void kf_wq_done(void* q, const char* key) {
  static_cast<WorkQueue*>(q)->Done(key);
}
int kf_wq_len(void* q) { return static_cast<WorkQueue*>(q)->Len(); }
void kf_wq_shutdown(void* q) { static_cast<WorkQueue*>(q)->ShutDown(); }
int kf_wq_shutting_down(void* q) {
  return static_cast<WorkQueue*>(q)->ShuttingDown() ? 1 : 0;
}
void kf_free(void* p) { free(p); }

}  // extern "C"
