// Metadata/lineage store — the MLMD analogue, the platform's one upstream
// C++ service (SURVEY.md §2.6/§2.8: ml-metadata store server ships with
// Pipelines). Artifacts, executions, and input/output events with lineage
// queries, persisted to an append-only escaped-record log (no sqlite dev
// headers in this environment) and replayed into an in-memory index on open.
//
// Wire format for query results (parsed by the ctypes wrapper):
//   fields separated by 0x1F (unit sep), records by 0x1E (record sep).
// Log format: one escaped line per record; '\\', '\n', 0x1F, 0x1E escaped.

#include <cstdio>
#include <cstring>
#include <ctime>
#include <fstream>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <vector>

namespace {

constexpr char kFS = '\x1f';  // field separator
constexpr char kRS = '\x1e';  // record separator

std::string Escape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\x1f': out += "\\f"; break;
      case '\x1e': out += "\\r"; break;
      default: out += c;
    }
  }
  return out;
}

std::string Unescape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '\\' && i + 1 < s.size()) {
      switch (s[++i]) {
        case '\\': out += '\\'; break;
        case 'n': out += '\n'; break;
        case 'f': out += '\x1f'; break;
        case 'r': out += '\x1e'; break;
        default: out += s[i];
      }
    } else {
      out += s[i];
    }
  }
  return out;
}

std::vector<std::string> SplitFields(const std::string& s) {
  std::vector<std::string> out;
  std::string cur;
  for (char c : s) {
    if (c == kFS) {
      out.push_back(cur);
      cur.clear();
    } else {
      cur += c;
    }
  }
  out.push_back(cur);
  return out;
}

struct Artifact {
  long long id;
  std::string type, name, uri, props;
  long long ts;
};

struct Execution {
  long long id;
  std::string type, name, state, props;
  long long ts;
};

struct Event {
  long long execution_id, artifact_id;
  int direction;  // 0 = input, 1 = output
  long long ts;
};

class MetaStore {
 public:
  explicit MetaStore(const std::string& path) : path_(path) {
    Replay();
    log_.open(path_, std::ios::app);
  }

  long long PutArtifact(long long id, const std::string& type,
                        const std::string& name, const std::string& uri,
                        const std::string& props) {
    std::lock_guard<std::mutex> lk(mu_);
    if (id == 0) id = ++next_artifact_id_;
    else if (id > next_artifact_id_) next_artifact_id_ = id;
    Artifact a{id, type, name, uri, props, Now()};
    artifacts_[id] = a;
    AppendLog('A', SerializeArtifact(a));
    return id;
  }

  long long PutExecution(long long id, const std::string& type,
                         const std::string& name, const std::string& state,
                         const std::string& props) {
    std::lock_guard<std::mutex> lk(mu_);
    if (id == 0) id = ++next_execution_id_;
    else if (id > next_execution_id_) next_execution_id_ = id;
    Execution e{id, type, name, state, props, Now()};
    executions_[id] = e;
    AppendLog('E', SerializeExecution(e));
    return id;
  }

  int PutEvent(long long exec_id, long long art_id, int direction) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!executions_.count(exec_id) || !artifacts_.count(art_id)) return -1;
    Event v{exec_id, art_id, direction, Now()};
    events_.push_back(v);
    AppendLog('V', SerializeEvent(v));
    return 0;
  }

  std::string GetArtifact(long long id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = artifacts_.find(id);
    return it == artifacts_.end() ? "" : SerializeArtifact(it->second);
  }

  std::string GetExecution(long long id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = executions_.find(id);
    return it == executions_.end() ? "" : SerializeExecution(it->second);
  }

  std::string ListArtifacts(const std::string& type) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out;
    for (auto& [id, a] : artifacts_) {
      if (!type.empty() && a.type != type) continue;
      if (!out.empty()) out += kRS;
      out += SerializeArtifact(a);
    }
    return out;
  }

  std::string ListExecutions(const std::string& type) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out;
    for (auto& [id, e] : executions_) {
      if (!type.empty() && e.type != type) continue;
      if (!out.empty()) out += kRS;
      out += SerializeExecution(e);
    }
    return out;
  }

  std::string EventsFor(long long exec_id, long long art_id) {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out;
    for (auto& v : events_) {
      if (exec_id != 0 && v.execution_id != exec_id) continue;
      if (art_id != 0 && v.artifact_id != art_id) continue;
      if (!out.empty()) out += kRS;
      out += SerializeEvent(v);
    }
    return out;
  }

 private:
  static long long Now() {
    return static_cast<long long>(::time(nullptr));
  }

  // Fields are escaped individually so a field may contain any byte,
  // including the separators and newlines.
  std::string SerializeArtifact(const Artifact& a) {
    std::ostringstream os;
    os << a.id << kFS << Escape(a.type) << kFS << Escape(a.name) << kFS
       << Escape(a.uri) << kFS << Escape(a.props) << kFS << a.ts;
    return os.str();
  }

  std::string SerializeExecution(const Execution& e) {
    std::ostringstream os;
    os << e.id << kFS << Escape(e.type) << kFS << Escape(e.name) << kFS
       << Escape(e.state) << kFS << Escape(e.props) << kFS << e.ts;
    return os.str();
  }

  std::string SerializeEvent(const Event& v) {
    std::ostringstream os;
    os << v.execution_id << kFS << v.artifact_id << kFS << v.direction << kFS
       << v.ts;
    return os.str();
  }

  void AppendLog(char tag, const std::string& record) {
    // record fields are already escaped; no raw newlines remain
    log_ << tag << record << "\n";
    log_.flush();
  }

  void Replay() {
    std::ifstream in(path_);
    std::string line;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      char tag = line[0];
      auto f = SplitFields(line.substr(1));
      if (tag == 'A' && f.size() == 6) {
        Artifact a{atoll(f[0].c_str()), Unescape(f[1]), Unescape(f[2]),
                   Unescape(f[3]), Unescape(f[4]), atoll(f[5].c_str())};
        artifacts_[a.id] = a;
        if (a.id > next_artifact_id_) next_artifact_id_ = a.id;
      } else if (tag == 'E' && f.size() == 6) {
        Execution e{atoll(f[0].c_str()), Unescape(f[1]), Unescape(f[2]),
                    Unescape(f[3]), Unescape(f[4]), atoll(f[5].c_str())};
        executions_[e.id] = e;
        if (e.id > next_execution_id_) next_execution_id_ = e.id;
      } else if (tag == 'V' && f.size() == 4) {
        events_.push_back(Event{atoll(f[0].c_str()), atoll(f[1].c_str()),
                                atoi(f[2].c_str()), atoll(f[3].c_str())});
      }
    }
  }

  std::mutex mu_;
  std::string path_;
  std::ofstream log_;
  std::map<long long, Artifact> artifacts_;
  std::map<long long, Execution> executions_;
  std::vector<Event> events_;
  long long next_artifact_id_ = 0;
  long long next_execution_id_ = 0;
};

}  // namespace

extern "C" {

void* kf_ms_open(const char* path) { return new MetaStore(path); }
void kf_ms_close(void* h) { delete static_cast<MetaStore*>(h); }

long long kf_ms_put_artifact(void* h, long long id, const char* type,
                             const char* name, const char* uri,
                             const char* props) {
  return static_cast<MetaStore*>(h)->PutArtifact(id, type, name, uri, props);
}
long long kf_ms_put_execution(void* h, long long id, const char* type,
                              const char* name, const char* state,
                              const char* props) {
  return static_cast<MetaStore*>(h)->PutExecution(id, type, name, state,
                                                  props);
}
int kf_ms_put_event(void* h, long long exec_id, long long art_id,
                    int direction) {
  return static_cast<MetaStore*>(h)->PutEvent(exec_id, art_id, direction);
}

static char* ToC(const std::string& s) {
  if (s.empty()) return nullptr;
  return strdup(s.c_str());
}

char* kf_ms_get_artifact(void* h, long long id) {
  return ToC(static_cast<MetaStore*>(h)->GetArtifact(id));
}
char* kf_ms_get_execution(void* h, long long id) {
  return ToC(static_cast<MetaStore*>(h)->GetExecution(id));
}
char* kf_ms_list_artifacts(void* h, const char* type) {
  return ToC(static_cast<MetaStore*>(h)->ListArtifacts(type ? type : ""));
}
char* kf_ms_list_executions(void* h, const char* type) {
  return ToC(static_cast<MetaStore*>(h)->ListExecutions(type ? type : ""));
}
char* kf_ms_events(void* h, long long exec_id, long long art_id) {
  return ToC(static_cast<MetaStore*>(h)->EventsFor(exec_id, art_id));
}

}  // extern "C"
