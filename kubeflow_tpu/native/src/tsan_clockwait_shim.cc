// TSan-build-only shim. glibc >= 2.30 implements
// std::condition_variable::wait_until via pthread_cond_clockwait, which
// older libtsan runtimes (gcc <= 10) do NOT intercept: TSan then never
// observes the mutex release inside the wait and reports false "double
// lock of a mutex" / data races on everything the lock protects.
//
// Defining the symbol in the main binary interposes BOTH glibc's version
// and (on newer toolchains) libtsan's interceptor, and forwards to
// pthread_cond_timedwait — which every libtsan intercepts — after
// rebasing a CLOCK_MONOTONIC absolute deadline onto CLOCK_REALTIME.
// Clock skew during the rebase only shifts a timeout by nanoseconds; the
// selftest's waits all tolerate that. Linked ONLY into selftest_tsan.

#include <pthread.h>
#include <time.h>

#include <cstdint>

extern "C" int pthread_cond_clockwait(pthread_cond_t* cond,
                                      pthread_mutex_t* mu, clockid_t clk,
                                      const struct timespec* abstime) {
  struct timespec target = *abstime;
  if (clk == CLOCK_MONOTONIC) {
    struct timespec mono, real;
    clock_gettime(CLOCK_MONOTONIC, &mono);
    clock_gettime(CLOCK_REALTIME, &real);
    int64_t delta_ns =
        (static_cast<int64_t>(abstime->tv_sec) - mono.tv_sec) * 1000000000LL +
        (abstime->tv_nsec - mono.tv_nsec);
    if (delta_ns < 0) delta_ns = 0;
    int64_t tgt_ns =
        static_cast<int64_t>(real.tv_sec) * 1000000000LL + real.tv_nsec +
        delta_ns;
    target.tv_sec = static_cast<time_t>(tgt_ns / 1000000000LL);
    target.tv_nsec = static_cast<long>(tgt_ns % 1000000000LL);
  }
  return pthread_cond_timedwait(cond, mu, &target);
}
