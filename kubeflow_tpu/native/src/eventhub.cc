// Event hub — the informer watch fan-out, native.
//
// The reference's Go controllers share one informer event pipeline
// (client-go sharedIndexInformer: apiserver watch -> bounded per-consumer
// delivery, slow consumers forced to relist — SURVEY.md §2.8 native ledger,
// "Go controller machinery"). This is that pipeline's core: a broadcast hub
// with per-subscriber bounded ring buffers. Publish assigns a global
// sequence number; each subscriber drains at its own pace; a subscriber
// that falls more than `capacity` behind is marked OVERFLOWED and must
// relist (the k8s "watch too old / resourceVersion expired" semantics —
// the Python fan-out this replaces grew unbounded queues under slow REST
// watchers).
//
// The hub carries only (seq, etype, kind, key) — object snapshots stay on
// the Python side in a deque bounded to the same capacity, so memory is
// bounded end-to-end and the C ABI stays string-simple.
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <sstream>
#include <string>

namespace {

struct Event {
  int64_t seq;
  int etype;
  std::string kind;
  std::string key;
};

// one selector term: label key -> required value. kPresenceOnly (a control
// byte the %-escaped wire format can never contain) means "key present,
// any value"; an EMPTY string is a real equality-to-empty-value match
// (k8s `labelSelector=team=` form) — the two must not be conflated or a
// stream's live tail and its Python-side relist diverge.
const char kPresenceOnly[] = "\x01";
using Selector = std::map<std::string, std::string>;

struct Subscriber {
  std::deque<Event> buf;
  bool overflowed = false;
  // server-side filter: kind -> label selector (empty selector = every
  // object of that kind). Empty map = all kinds, no filtering. Filtered-
  // out events are never buffered, so an unrelated storm can neither
  // overflow this subscriber nor cost it per-event resolution work (the
  // control-plane fan-out fix: previously every subscriber received
  // every event and discarded irrelevant ones in Python, and at 10k pods
  // that client-side discard WAS the concurrency ceiling).
  std::map<std::string, Selector> filters;
  // per-subscriber wakeup: Publish notifies only the subscribers that
  // actually RECEIVED the event — a hub-wide cv made every publish wake
  // every idle watcher (8 bystanders x 20k events = 160k spurious
  // scheduler round-trips in a 10k-pod storm). shared_ptr so a Poll
  // blocked on it survives a racing Unsubscribe.
  std::shared_ptr<std::condition_variable> cv =
      std::make_shared<std::condition_variable>();
};

class EventHub {
 public:
  explicit EventHub(int capacity) : capacity_(capacity < 1 ? 1 : capacity) {}

  // filter_spec: "kind[:k[=v][,k2[=v2]]];kind2..." — per-kind label
  // selectors; empty/null = all kinds. A selector term without '=' means
  // "label key present, any value".
  int64_t Subscribe(const char* filter_spec) {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t id = next_sub_++;
    Subscriber sub;
    if (filter_spec != nullptr && filter_spec[0] != '\0') {
      std::stringstream ss(filter_spec);
      std::string entry;
      while (std::getline(ss, entry, ';')) {
        if (entry.empty()) continue;
        auto colon = entry.find(':');
        std::string kind = entry.substr(0, colon);
        Selector sel;
        if (colon != std::string::npos) {
          std::stringstream terms(entry.substr(colon + 1));
          std::string term;
          while (std::getline(terms, term, ',')) {
            if (term.empty()) continue;
            auto eq = term.find('=');
            if (eq == std::string::npos) {
              sel[term] = kPresenceOnly;
            } else {
              sel[term.substr(0, eq)] = term.substr(eq + 1);
            }
          }
        }
        if (!kind.empty()) sub.filters[kind] = std::move(sel);
      }
    }
    subs_.emplace(id, std::move(sub));
    return id;
  }

  void Unsubscribe(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = subs_.find(id);
    if (it == subs_.end()) return;
    // wake any Poll blocked on this subscriber before the entry goes:
    // it re-locks, re-finds, and reports GONE
    it->second.cv->notify_all();
    subs_.erase(it);
  }

  // labels_csv: the object's labels as "k=v,k2=v2" (may be empty) —
  // parsed at most once per publish, and only when some subscriber
  // actually carries a label selector for this kind.
  int64_t Publish(int etype, const char* kind, const char* key,
                  const char* labels_csv) {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t seq = next_seq_++;
    std::map<std::string, std::string> labels;
    bool labels_parsed = false;
    for (auto& [id, sub] : subs_) {
      if (!sub.filters.empty()) {
        auto it = sub.filters.find(kind);
        if (it == sub.filters.end()) continue;  // kind filtered out
        const Selector& sel = it->second;
        if (!sel.empty()) {
          if (!labels_parsed) {
            labels_parsed = true;
            if (labels_csv != nullptr && labels_csv[0] != '\0') {
              std::stringstream ss(labels_csv);
              std::string term;
              while (std::getline(ss, term, ',')) {
                auto eq = term.find('=');
                if (eq != std::string::npos) {
                  labels[term.substr(0, eq)] = term.substr(eq + 1);
                }
              }
            }
          }
          bool match = true;
          for (const auto& [k, v] : sel) {
            auto l = labels.find(k);
            if (l == labels.end() ||
                (v != kPresenceOnly && l->second != v)) {
              match = false;
              break;
            }
          }
          if (!match) continue;  // label-selector filtered out
        }
      }
      if (sub.overflowed) continue;  // already requires a relist
      if (static_cast<int>(sub.buf.size()) >= capacity_) {
        // slow consumer: drop its backlog, force relist
        sub.buf.clear();
        sub.overflowed = true;
        sub.cv->notify_all();  // an overflow IS a deliverable condition
        continue;
      }
      sub.buf.push_back(Event{seq, etype, kind, key});
      sub.cv->notify_all();
    }
    return seq;
  }

  // rc: 0 = event written to out params, 1 = timeout/empty, 2 = overflowed
  // (cleared — caller must relist), 3 = unknown subscriber.
  int Poll(int64_t id, double timeout_s, int64_t* seq, int* etype,
           std::string* kind, std::string* key) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<std::chrono::nanoseconds>(
                        std::chrono::duration<double>(timeout_s < 0 ? 0 : timeout_s));
    for (;;) {
      auto it = subs_.find(id);
      if (it == subs_.end()) return 3;
      Subscriber& sub = it->second;
      if (sub.overflowed) {
        sub.overflowed = false;
        return 2;
      }
      if (!sub.buf.empty()) {
        Event ev = sub.buf.front();
        sub.buf.pop_front();
        *seq = ev.seq;
        *etype = ev.etype;
        *kind = ev.kind;
        *key = ev.key;
        return 0;
      }
      // local shared_ptr: the cv outlives a racing Unsubscribe (which
      // notifies first, so this wait wakes and reports GONE)
      std::shared_ptr<std::condition_variable> cv = sub.cv;
      if (timeout_s <= 0 ||
          cv->wait_until(lk, deadline) == std::cv_status::timeout) {
        auto again = subs_.find(id);
        if (again == subs_.end()) return 3;
        if (again->second.overflowed) {
          again->second.overflowed = false;
          return 2;
        }
        if (!again->second.buf.empty()) continue;  // raced a publish
        return 1;
      }
    }
  }

  int Backlog(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = subs_.find(id);
    return it == subs_.end() ? -1 : static_cast<int>(it->second.buf.size());
  }

 private:
  std::mutex mu_;
  std::map<int64_t, Subscriber> subs_;
  int capacity_;
  int64_t next_sub_ = 1;
  int64_t next_seq_ = 1;
};

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

}  // namespace

extern "C" {

void* kf_hub_new(int capacity) { return new EventHub(capacity); }
void kf_hub_free(void* h) { delete static_cast<EventHub*>(h); }

long long kf_hub_subscribe(void* h) {
  return static_cast<EventHub*>(h)->Subscribe(nullptr);
}

// filter_spec: "kind[:k[=v][,k2]];kind2..." per-kind label selectors;
// ""/null = all kinds unfiltered.
long long kf_hub_subscribe_filtered(void* h, const char* filter_spec) {
  return static_cast<EventHub*>(h)->Subscribe(filter_spec);
}

void kf_hub_unsubscribe(void* h, long long id) {
  static_cast<EventHub*>(h)->Unsubscribe(id);
}

long long kf_hub_publish(void* h, int etype, const char* kind,
                         const char* key) {
  return static_cast<EventHub*>(h)->Publish(etype, kind, key, nullptr);
}

// publish with the object's labels ("k=v,k2=v2") so label-selector
// subscribers can be matched server-side.
long long kf_hub_publish_labeled(void* h, int etype, const char* kind,
                                 const char* key, const char* labels_csv) {
  return static_cast<EventHub*>(h)->Publish(etype, kind, key, labels_csv);
}

// rc as in EventHub::Poll; on rc==0, *out_seq/*out_etype are set and
// *out_kind/*out_key are malloc'd strings the caller frees via kf_free.
int kf_hub_poll(void* h, long long id, double timeout_s, long long* out_seq,
                int* out_etype, char** out_kind, char** out_key) {
  int64_t seq = 0;
  int etype = 0;
  std::string kind, key;
  int rc = static_cast<EventHub*>(h)->Poll(id, timeout_s, &seq, &etype,
                                           &kind, &key);
  if (rc == 0) {
    *out_seq = seq;
    *out_etype = etype;
    *out_kind = dup_string(kind);
    *out_key = dup_string(key);
  }
  return rc;
}

int kf_hub_backlog(void* h, long long id) {
  return static_cast<EventHub*>(h)->Backlog(id);
}

}  // extern "C"
