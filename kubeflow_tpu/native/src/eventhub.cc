// Event hub — the informer watch fan-out, native.
//
// The reference's Go controllers share one informer event pipeline
// (client-go sharedIndexInformer: apiserver watch -> bounded per-consumer
// delivery, slow consumers forced to relist — SURVEY.md §2.8 native ledger,
// "Go controller machinery"). This is that pipeline's core: a broadcast hub
// with per-subscriber bounded ring buffers. Publish assigns a global
// sequence number; each subscriber drains at its own pace; a subscriber
// that falls more than `capacity` behind is marked OVERFLOWED and must
// relist (the k8s "watch too old / resourceVersion expired" semantics —
// the Python fan-out this replaces grew unbounded queues under slow REST
// watchers).
//
// The hub carries only (seq, etype, kind, key) — object snapshots stay on
// the Python side in a deque bounded to the same capacity, so memory is
// bounded end-to-end and the C ABI stays string-simple.
//
// Exposed via a C ABI for ctypes (no pybind11 in this environment).

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>

namespace {

struct Event {
  int64_t seq;
  int etype;
  std::string kind;
  std::string key;
};

struct Subscriber {
  std::deque<Event> buf;
  bool overflowed = false;
};

class EventHub {
 public:
  explicit EventHub(int capacity) : capacity_(capacity < 1 ? 1 : capacity) {}

  int64_t Subscribe() {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t id = next_sub_++;
    subs_.emplace(id, Subscriber{});
    return id;
  }

  void Unsubscribe(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    subs_.erase(id);
  }

  int64_t Publish(int etype, const char* kind, const char* key) {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t seq = next_seq_++;
    for (auto& [id, sub] : subs_) {
      if (sub.overflowed) continue;  // already requires a relist
      if (static_cast<int>(sub.buf.size()) >= capacity_) {
        // slow consumer: drop its backlog, force relist
        sub.buf.clear();
        sub.overflowed = true;
        continue;
      }
      sub.buf.push_back(Event{seq, etype, kind, key});
    }
    cv_.notify_all();
    return seq;
  }

  // rc: 0 = event written to out params, 1 = timeout/empty, 2 = overflowed
  // (cleared — caller must relist), 3 = unknown subscriber.
  int Poll(int64_t id, double timeout_s, int64_t* seq, int* etype,
           std::string* kind, std::string* key) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<std::chrono::nanoseconds>(
                        std::chrono::duration<double>(timeout_s < 0 ? 0 : timeout_s));
    for (;;) {
      auto it = subs_.find(id);
      if (it == subs_.end()) return 3;
      Subscriber& sub = it->second;
      if (sub.overflowed) {
        sub.overflowed = false;
        return 2;
      }
      if (!sub.buf.empty()) {
        Event ev = sub.buf.front();
        sub.buf.pop_front();
        *seq = ev.seq;
        *etype = ev.etype;
        *kind = ev.kind;
        *key = ev.key;
        return 0;
      }
      if (timeout_s <= 0 ||
          cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
        auto again = subs_.find(id);
        if (again == subs_.end()) return 3;
        if (again->second.overflowed) {
          again->second.overflowed = false;
          return 2;
        }
        if (!again->second.buf.empty()) continue;  // raced a publish
        return 1;
      }
    }
  }

  int Backlog(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = subs_.find(id);
    return it == subs_.end() ? -1 : static_cast<int>(it->second.buf.size());
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<int64_t, Subscriber> subs_;
  int capacity_;
  int64_t next_sub_ = 1;
  int64_t next_seq_ = 1;
};

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

}  // namespace

extern "C" {

void* kf_hub_new(int capacity) { return new EventHub(capacity); }
void kf_hub_free(void* h) { delete static_cast<EventHub*>(h); }

long long kf_hub_subscribe(void* h) {
  return static_cast<EventHub*>(h)->Subscribe();
}

void kf_hub_unsubscribe(void* h, long long id) {
  static_cast<EventHub*>(h)->Unsubscribe(id);
}

long long kf_hub_publish(void* h, int etype, const char* kind,
                         const char* key) {
  return static_cast<EventHub*>(h)->Publish(etype, kind, key);
}

// rc as in EventHub::Poll; on rc==0, *out_seq/*out_etype are set and
// *out_kind/*out_key are malloc'd strings the caller frees via kf_free.
int kf_hub_poll(void* h, long long id, double timeout_s, long long* out_seq,
                int* out_etype, char** out_kind, char** out_key) {
  int64_t seq = 0;
  int etype = 0;
  std::string kind, key;
  int rc = static_cast<EventHub*>(h)->Poll(id, timeout_s, &seq, &etype,
                                           &kind, &key);
  if (rc == 0) {
    *out_seq = seq;
    *out_etype = etype;
    *out_kind = dup_string(kind);
    *out_key = dup_string(key);
  }
  return rc;
}

int kf_hub_backlog(void* h, long long id) {
  return static_cast<EventHub*>(h)->Backlog(id);
}

}  // extern "C"
