// ControllerExpectations — the logical race guard between informer-cache
// catch-up and reconcile (SURVEY.md §5.2: prevents duplicate pod creation).
//
// A reconciler that just created N pods must not create them again on the
// next (stale-cache) reconcile: it records ExpectCreations(key, N); observed
// creations decrement; SatisfiedExpectations gates the next creation pass.
// Expectations expire after a TTL so a lost watch event can't deadlock the
// controller (same 5-minute default as the reference).

#include <chrono>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {

using Clock = std::chrono::steady_clock;

struct Expectation {
  long long adds = 0;
  long long dels = 0;
  Clock::time_point stamp;
};

class Expectations {
 public:
  explicit Expectations(double ttl_s) : ttl_(ttl_s) {}

  void ExpectCreations(const std::string& key, long long n) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& e = map_[key];
    e.adds = n;
    e.dels = 0;
    e.stamp = Clock::now();
  }

  void ExpectDeletions(const std::string& key, long long n) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& e = map_[key];
    e.dels = n;
    e.stamp = Clock::now();
  }

  void CreationObserved(const std::string& key) { Lower(key, true); }
  void DeletionObserved(const std::string& key) { Lower(key, false); }

  // True when no outstanding expectations (or they expired / were never set).
  bool Satisfied(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = map_.find(key);
    if (it == map_.end()) return true;
    const auto& e = it->second;
    if (e.adds <= 0 && e.dels <= 0) return true;
    double age =
        std::chrono::duration<double>(Clock::now() - e.stamp).count();
    return age > ttl_;  // expired: force a fresh reconcile pass
  }

  void Delete(const std::string& key) {
    std::lock_guard<std::mutex> lk(mu_);
    map_.erase(key);
  }

  void Counts(const std::string& key, long long* adds, long long* dels) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = map_.find(key);
    *adds = it == map_.end() ? 0 : it->second.adds;
    *dels = it == map_.end() ? 0 : it->second.dels;
  }

 private:
  void Lower(const std::string& key, bool add) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = map_.find(key);
    if (it == map_.end()) return;
    auto& e = it->second;
    if (add && e.adds > 0) e.adds--;
    if (!add && e.dels > 0) e.dels--;
  }

  std::mutex mu_;
  std::map<std::string, Expectation> map_;
  double ttl_;
};

}  // namespace

extern "C" {

void* kf_exp_new(double ttl_s) { return new Expectations(ttl_s); }
void kf_exp_free(void* e) { delete static_cast<Expectations*>(e); }
void kf_exp_expect_creations(void* e, const char* key, long long n) {
  static_cast<Expectations*>(e)->ExpectCreations(key, n);
}
void kf_exp_expect_deletions(void* e, const char* key, long long n) {
  static_cast<Expectations*>(e)->ExpectDeletions(key, n);
}
void kf_exp_creation_observed(void* e, const char* key) {
  static_cast<Expectations*>(e)->CreationObserved(key);
}
void kf_exp_deletion_observed(void* e, const char* key) {
  static_cast<Expectations*>(e)->DeletionObserved(key);
}
int kf_exp_satisfied(void* e, const char* key) {
  return static_cast<Expectations*>(e)->Satisfied(key) ? 1 : 0;
}
void kf_exp_delete(void* e, const char* key) {
  static_cast<Expectations*>(e)->Delete(key);
}
void kf_exp_counts(void* e, const char* key, long long* adds,
                   long long* dels) {
  static_cast<Expectations*>(e)->Counts(key, adds, dels);
}

}  // extern "C"
