// Self-test binary for sanitizer runs (make check / make tsan).
// Hammers the workqueue from multiple producer/consumer threads and
// exercises expectations + metastore round-trips. Exit 0 = pass.

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* kf_wq_new(double, double);
void kf_wq_free(void*);
void kf_wq_add(void*, const char*);
void kf_wq_add_after(void*, const char*, double);
double kf_wq_add_rate_limited(void*, const char*);
void kf_wq_forget(void*, const char*);
int kf_wq_num_requeues(void*, const char*);
char* kf_wq_get(void*, double);
void kf_wq_done(void*, const char*);
int kf_wq_len(void*);
void kf_wq_shutdown(void*);
void kf_free(void*);

void* kf_exp_new(double);
void kf_exp_free(void*);
void kf_exp_expect_creations(void*, const char*, long long);
void kf_exp_creation_observed(void*, const char*);
int kf_exp_satisfied(void*, const char*);
void kf_exp_delete(void*, const char*);

void* kf_hub_new(int);
void kf_hub_free(void*);
long long kf_hub_subscribe(void*);
void kf_hub_unsubscribe(void*, long long);
long long kf_hub_publish(void*, int, const char*, const char*);
int kf_hub_poll(void*, long long, double, long long*, int*, char**, char**);
int kf_hub_backlog(void*, long long);

void* kf_rd_new(void*, int, int (*)(const char*, double*));
void kf_rd_stop(void*);
void kf_rd_free(void*);
long kf_rd_total(void*);
long kf_rd_errors(void*);
long kf_rd_conflicts(void*);

void* kf_ms_open(const char*);
void kf_ms_close(void*);
long long kf_ms_put_artifact(void*, long long, const char*, const char*,
                             const char*, const char*);
long long kf_ms_put_execution(void*, long long, const char*, const char*,
                              const char*, const char*);
int kf_ms_put_event(void*, long long, long long, int);
char* kf_ms_get_artifact(void*, long long);
char* kf_ms_list_artifacts(void*, const char*);
char* kf_ms_events(void*, long long, long long);
}

int main() {
  // --- workqueue: concurrent producers + consumers, every item processed.
  void* q = kf_wq_new(0.001, 0.1);
  std::atomic<int> processed{0};
  const int kProducers = 4, kPerProducer = 500, kConsumers = 4;

  std::vector<std::thread> consumers;
  for (int c = 0; c < kConsumers; ++c) {
    consumers.emplace_back([&] {
      while (true) {
        char* key = kf_wq_get(q, 5.0);
        if (!key) break;
        processed++;
        kf_wq_done(q, key);
        kf_free(key);
      }
    });
  }
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&, p] {
      for (int i = 0; i < kPerProducer; ++i) {
        std::string key = "job-" + std::to_string(p) + "-" + std::to_string(i);
        kf_wq_add(q, key.c_str());
        if (i % 50 == 0) kf_wq_add_after(q, key.c_str(), 0.002);
      }
    });
  }
  for (auto& t : producers) t.join();
  // dedupe means processed <= adds; wait for drain then shut down.
  while (kf_wq_len(q) > 0) std::this_thread::yield();
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  kf_wq_shutdown(q);
  for (auto& t : consumers) t.join();
  assert(processed.load() >= kProducers * kPerProducer / 2);
  kf_wq_free(q);

  // rate limiting: monotone growing backoff until forget
  void* q2 = kf_wq_new(0.01, 1.0);
  double d1 = kf_wq_add_rate_limited(q2, "x");
  double d2 = kf_wq_add_rate_limited(q2, "x");
  double d3 = kf_wq_add_rate_limited(q2, "x");
  assert(d1 < d2 && d2 < d3);
  assert(kf_wq_num_requeues(q2, "x") == 3);
  kf_wq_forget(q2, "x");
  assert(kf_wq_num_requeues(q2, "x") == 0);
  kf_wq_shutdown(q2);
  kf_wq_free(q2);

  // --- reconcile driver: native workers drain concurrent adds through a
  // callback that succeeds, conflicts, or errors by key class; every error/
  // conflict key is rate-limit-requeued and eventually succeeds (callback
  // consults a shared attempt map).
  {
    static std::atomic<int> ok_calls{0};
    static std::atomic<int> flaky_first{0};
    void* q3 = kf_wq_new(0.001, 0.05);
    void* rd = kf_rd_new(
        q3, 3, [](const char* key, double* after) -> int {
          if (strstr(key, "requeue")) {
            static std::atomic<int> requeue_once{0};
            *after = requeue_once.fetch_add(1) == 0 ? 0.001 : -1.0;
            return 0;
          }
          if (strstr(key, "conflict")) {
            // conflict exactly once, then succeed
            return flaky_first.fetch_add(1) == 0 ? 1 : 0;
          }
          if (strstr(key, "error")) {
            static std::atomic<int> err_once{0};
            return err_once.fetch_add(1) == 0 ? 2 : 0;
          }
          ok_calls.fetch_add(1);
          return 0;
        });
    for (int i = 0; i < 200; ++i) {
      std::string key = "ok-" + std::to_string(i);
      kf_wq_add(q3, key.c_str());
    }
    kf_wq_add(q3, "conflict-1");
    kf_wq_add(q3, "error-1");
    kf_wq_add(q3, "requeue-1");
    // drain: all keys processed, retries included
    while (kf_wq_len(q3) > 0) std::this_thread::yield();
    std::this_thread::sleep_for(std::chrono::milliseconds(300));
    kf_wq_shutdown(q3);
    kf_rd_stop(rd);
    assert(kf_rd_total(rd) >= 203);
    assert(kf_rd_errors(rd) == 1);
    assert(kf_rd_conflicts(rd) == 1);
    kf_rd_free(rd);
    kf_wq_free(q3);
  }

  // --- expectations: concurrent observers race against Satisfied readers.
  void* e = kf_exp_new(300.0);
  kf_exp_expect_creations(e, "ns/job", 100);
  assert(!kf_exp_satisfied(e, "ns/job"));
  std::vector<std::thread> observers;
  for (int i = 0; i < 4; ++i) {
    observers.emplace_back([&] {
      for (int j = 0; j < 25; ++j) kf_exp_creation_observed(e, "ns/job");
    });
  }
  std::thread reader([&] {
    for (int j = 0; j < 1000; ++j) kf_exp_satisfied(e, "ns/job");
  });
  for (auto& t : observers) t.join();
  reader.join();
  assert(kf_exp_satisfied(e, "ns/job"));
  kf_exp_free(e);

  // --- metastore: round-trip with hostile bytes + replay.
  const char* path = "/tmp/kf_selftest_meta.log";
  remove(path);
  void* ms = kf_ms_open(path);
  long long a =
      kf_ms_put_artifact(ms, 0, "model", "m\nodel\x1f", "gs://b/m", "{\"k\":1}");
  long long x = kf_ms_put_execution(ms, 0, "train", "run1", "RUNNING", "{}");
  assert(kf_ms_put_event(ms, x, a, 1) == 0);
  assert(kf_ms_put_event(ms, 999999, a, 1) == -1);
  kf_ms_close(ms);

  ms = kf_ms_open(path);  // replay
  char* got = kf_ms_get_artifact(ms, a);
  assert(got && strstr(got, "gs://b/m"));
  kf_free(got);
  char* evs = kf_ms_events(ms, x, 0);
  assert(evs);
  kf_free(evs);
  kf_ms_close(ms);
  remove(path);

  // --- event hub: broadcast under contention + slow-consumer overflow.
  void* hub = kf_hub_new(64);
  long long fast = kf_hub_subscribe(hub);
  long long slow = kf_hub_subscribe(hub);
  std::atomic<int> fast_got{0};
  std::thread hub_consumer([&] {
    long long seq;
    int etype;
    char* kind;
    char* key;
    for (;;) {
      int rc = kf_hub_poll(hub, fast, 2.0, &seq, &etype, &kind, &key);
      if (rc == 0) {
        assert(strcmp(kind, "pods") == 0);
        kf_free(kind);
        kf_free(key);
        if (++fast_got == 300) return;
      } else if (rc == 1) {
        return;  // drained
      } else {
        assert(rc == 2);  // overflow is legal under sanitizer slowness
        return;
      }
    }
  });
  std::vector<std::thread> publishers;
  for (int t = 0; t < 3; t++) {
    publishers.emplace_back([&, t] {
      for (int i = 0; i < 100; i++) {
        char key[32];
        snprintf(key, sizeof key, "ns/p-%d-%d", t, i);
        kf_hub_publish(hub, 0, "pods", key);
      }
    });
  }
  for (auto& t : publishers) t.join();
  hub_consumer.join();
  // the slow subscriber never polled: its 64-slot buffer overflowed
  long long sseq;
  int setype;
  char* skind;
  char* skey;
  int src_rc = kf_hub_poll(hub, slow, 0.0, &sseq, &setype, &skind, &skey);
  assert(src_rc == 2);  // must relist
  assert(kf_hub_backlog(hub, slow) == 0);
  kf_hub_unsubscribe(hub, slow);
  assert(kf_hub_poll(hub, slow, 0.0, &sseq, &setype, &skind, &skey) == 3);
  kf_hub_free(hub);

  printf("selftest OK (processed=%d)\n", processed.load());
  return 0;
}
