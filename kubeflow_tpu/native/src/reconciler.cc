// Native reconcile driver — the worker half of the controller runtime.
//
// The reference's reconcile machinery is native (Go controller-runtime:
// worker goroutines draining a rate-limited queue — SURVEY.md §2.8 ledger
// item 2). Here C++ owns the same responsibilities: the worker thread pool,
// blocking dequeue, and the full requeue discipline (forget on success,
// AddAfter for requested requeues, exponential AddRateLimited on
// conflict/error, Done-with-dirty-replay). Only the business logic — one
// level-triggered reconcile(key) pass — calls back into Python through a C
// function pointer (ctypes acquires the GIL for foreign-thread callbacks).
//
// Callback contract:
//   int cb(const char* key, double* requeue_after_s)
//     return 0 = success  (requeue_after_s >= 0 → schedule a follow-up pass)
//            1 = conflict (benign optimistic-concurrency loss: rate-limited
//                          requeue, not counted as an error)
//            2 = error    (rate-limited requeue, error counter bumped)
//
// Layered strictly on the workqueue's C ABI so the queue stays the single
// source of truth for dedupe/dirty semantics.

#include <atomic>
#include <string>
#include <thread>
#include <vector>

extern "C" {
// workqueue.cc C ABI
char* kf_wq_get(void* q, double timeout_s);
void kf_wq_done(void* q, const char* key);
void kf_wq_forget(void* q, const char* key);
void kf_wq_add_after(void* q, const char* key, double delay_s);
double kf_wq_add_rate_limited(void* q, const char* key);
int kf_wq_shutting_down(void* q);
void kf_free(void* p);
}

namespace {

using ReconcileCb = int (*)(const char* key, double* requeue_after_s);

class ReconcileDriver {
 public:
  ReconcileDriver(void* wq, int n_workers, ReconcileCb cb)
      : wq_(wq), cb_(cb) {
    workers_.reserve(n_workers);
    for (int i = 0; i < n_workers; ++i) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~ReconcileDriver() { Stop(); }

  void Stop() {
    stop_.store(true);
    for (auto& t : workers_) {
      if (t.joinable()) t.join();
    }
    workers_.clear();
  }

  long Total() const { return total_.load(); }
  long Errors() const { return errors_.load(); }
  long Conflicts() const { return conflicts_.load(); }

 private:
  void WorkerLoop() {
    // stop_ is checked every iteration, not just on empty-queue timeouts:
    // Stop() must join promptly even against a never-draining queue.
    while (!stop_.load()) {
      char* raw = kf_wq_get(wq_, 0.5);
      if (raw == nullptr) {
        if (stop_.load() || kf_wq_shutting_down(wq_)) return;
        continue;
      }
      std::string key(raw);
      kf_free(raw);
      double after = -1.0;
      int rc = cb_(key.c_str(), &after);
      total_.fetch_add(1);
      if (rc == 0) {
        kf_wq_forget(wq_, key.c_str());
        if (after >= 0.0) kf_wq_add_after(wq_, key.c_str(), after);
      } else if (rc == 1) {
        conflicts_.fetch_add(1);
        kf_wq_add_rate_limited(wq_, key.c_str());
      } else {
        errors_.fetch_add(1);
        kf_wq_add_rate_limited(wq_, key.c_str());
      }
      kf_wq_done(wq_, key.c_str());
    }
  }

  void* wq_;
  ReconcileCb cb_;
  std::atomic<bool> stop_{false};
  std::atomic<long> total_{0};
  std::atomic<long> errors_{0};
  std::atomic<long> conflicts_{0};
  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* kf_rd_new(void* wq, int n_workers, ReconcileCb cb) {
  return new ReconcileDriver(wq, n_workers, cb);
}
// Stop joins the workers; the queue must already be shut down (or keys
// drained) for a prompt join — workers wake every 0.5 s regardless.
void kf_rd_stop(void* rd) { static_cast<ReconcileDriver*>(rd)->Stop(); }
void kf_rd_free(void* rd) { delete static_cast<ReconcileDriver*>(rd); }
long kf_rd_total(void* rd) { return static_cast<ReconcileDriver*>(rd)->Total(); }
long kf_rd_errors(void* rd) {
  return static_cast<ReconcileDriver*>(rd)->Errors();
}
long kf_rd_conflicts(void* rd) {
  return static_cast<ReconcileDriver*>(rd)->Conflicts();
}

}  // extern "C"
