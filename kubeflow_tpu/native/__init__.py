"""ctypes bindings for the native core (libkfcore.so).

Native components (SURVEY.md §2.8 ledger): work queue + expectations (the
reference's Go controller machinery) and the metadata store (the reference's
C++ MLMD server). Built on demand with `make`; sanitizer self-tests run via
`make check` (ASan/UBSan) and `make tsan`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import weakref
from pathlib import Path

_DIR = Path(__file__).parent
_LIB_PATH = _DIR / "build" / "libkfcore.so"
_BUILD_LOCK = threading.Lock()
_lib = None


def ensure_built() -> Path:
    """Build libkfcore.so if missing or stale (source newer than lib)."""
    srcs = sorted((_DIR / "src").glob("*.cc"))
    # selftest-only sources never link into the lib — not staleness signals
    _selftest_only = {"selftest.cc", "tsan_clockwait_shim.cc"}
    stale = not _LIB_PATH.exists() or any(
        s.stat().st_mtime > _LIB_PATH.stat().st_mtime
        for s in srcs
        if s.name not in _selftest_only
    )
    if stale:
        with _BUILD_LOCK:
            subprocess.run(
                ["make", str(_LIB_PATH.relative_to(_DIR))],
                cwd=_DIR,
                check=True,
                capture_output=True,
            )
    return _LIB_PATH


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        path = ensure_built()
        L = ctypes.CDLL(str(path))
        # workqueue
        L.kf_wq_new.restype = ctypes.c_void_p
        L.kf_wq_new.argtypes = [ctypes.c_double, ctypes.c_double]
        L.kf_wq_free.argtypes = [ctypes.c_void_p]
        L.kf_wq_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_wq_add_after.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
        L.kf_wq_add_rate_limited.restype = ctypes.c_double
        L.kf_wq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_wq_forget.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_wq_num_requeues.restype = ctypes.c_int
        L.kf_wq_num_requeues.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_wq_get.restype = ctypes.c_void_p  # manual free => void_p not char_p
        L.kf_wq_get.argtypes = [ctypes.c_void_p, ctypes.c_double]
        L.kf_wq_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_wq_len.restype = ctypes.c_int
        L.kf_wq_len.argtypes = [ctypes.c_void_p]
        L.kf_wq_shutdown.argtypes = [ctypes.c_void_p]
        L.kf_wq_shutting_down.restype = ctypes.c_int
        L.kf_wq_shutting_down.argtypes = [ctypes.c_void_p]
        L.kf_free.argtypes = [ctypes.c_void_p]
        # reconcile driver
        L.kf_rd_new.restype = ctypes.c_void_p
        L.kf_rd_new.argtypes = [ctypes.c_void_p, ctypes.c_int, RECONCILE_CB]
        L.kf_rd_stop.argtypes = [ctypes.c_void_p]
        L.kf_rd_free.argtypes = [ctypes.c_void_p]
        for fn in ("kf_rd_total", "kf_rd_errors", "kf_rd_conflicts"):
            getattr(L, fn).restype = ctypes.c_long
            getattr(L, fn).argtypes = [ctypes.c_void_p]
        # expectations
        L.kf_exp_new.restype = ctypes.c_void_p
        L.kf_exp_new.argtypes = [ctypes.c_double]
        L.kf_exp_free.argtypes = [ctypes.c_void_p]
        L.kf_exp_expect_creations.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
        L.kf_exp_expect_deletions.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
        L.kf_exp_creation_observed.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_exp_deletion_observed.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_exp_satisfied.restype = ctypes.c_int
        L.kf_exp_satisfied.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_exp_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_exp_counts.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ]
        # event hub
        L.kf_hub_new.restype = ctypes.c_void_p
        L.kf_hub_new.argtypes = [ctypes.c_int]
        L.kf_hub_free.argtypes = [ctypes.c_void_p]
        L.kf_hub_subscribe.restype = ctypes.c_longlong
        L.kf_hub_subscribe.argtypes = [ctypes.c_void_p]
        L.kf_hub_subscribe_filtered.restype = ctypes.c_longlong
        L.kf_hub_subscribe_filtered.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_hub_unsubscribe.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        L.kf_hub_publish.restype = ctypes.c_longlong
        L.kf_hub_publish.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ]
        L.kf_hub_publish_labeled.restype = ctypes.c_longlong
        L.kf_hub_publish_labeled.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        L.kf_hub_poll.restype = ctypes.c_int
        L.kf_hub_poll.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_double,
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
        ]
        L.kf_hub_backlog.restype = ctypes.c_int
        L.kf_hub_backlog.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        # metastore
        L.kf_ms_open.restype = ctypes.c_void_p
        L.kf_ms_open.argtypes = [ctypes.c_char_p]
        L.kf_ms_close.argtypes = [ctypes.c_void_p]
        L.kf_ms_put_artifact.restype = ctypes.c_longlong
        L.kf_ms_put_artifact.argtypes = [ctypes.c_void_p, ctypes.c_longlong] + [ctypes.c_char_p] * 4
        L.kf_ms_put_execution.restype = ctypes.c_longlong
        L.kf_ms_put_execution.argtypes = [ctypes.c_void_p, ctypes.c_longlong] + [ctypes.c_char_p] * 4
        L.kf_ms_put_event.restype = ctypes.c_int
        L.kf_ms_put_event.argtypes = [ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int]
        for fn in ("kf_ms_get_artifact", "kf_ms_get_execution"):
            getattr(L, fn).restype = ctypes.c_void_p
            getattr(L, fn).argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        for fn in ("kf_ms_list_artifacts", "kf_ms_list_executions"):
            getattr(L, fn).restype = ctypes.c_void_p
            getattr(L, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        L.kf_ms_events.restype = ctypes.c_void_p
        L.kf_ms_events.argtypes = [ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong]
        _lib = L
    return _lib


# int cb(const char* key, double* requeue_after_s) — see reconciler.cc for
# the 0/1/2 (ok/conflict/error) contract. ctypes acquires the GIL when the
# C++ worker threads invoke it.
RECONCILE_CB = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_double)
)


def _finalize_driver(L: ctypes.CDLL, h: int, cb) -> None:
    """Join + free a native driver. Runs via weakref.finalize — at GC of the
    wrapper OR at interpreter exit, whichever comes first — because a C++
    worker invoking the ctypes trampoline after the CFUNCTYPE object (or the
    interpreter) is gone is undefined behavior. `cb` is carried solely to
    keep the trampoline alive until the workers are joined. ctypes releases
    the GIL during kf_rd_stop, so in-flight callbacks can finish."""
    del cb  # alive until here — that's its whole job
    try:
        L.kf_rd_stop(h)
        L.kf_rd_free(h)
    except Exception:  # noqa: BLE001 — teardown must not raise
        pass


class ReconcileDriver:
    """Native worker pool draining a WorkQueue through a Python reconcile
    callback (reconciler.cc). C++ owns the threads and the full requeue
    discipline; the callback is the only Python on the hot path."""

    def __init__(self, wq: "WorkQueue", n_workers: int, callback):
        self._L = lib()
        # the CFUNCTYPE object must outlive the driver or C++ calls a
        # collected trampoline; _finalize_driver holds it until join
        self._cb = callback if isinstance(callback, RECONCILE_CB) else RECONCILE_CB(callback)
        self._h = self._L.kf_rd_new(wq._h, n_workers, self._cb)
        self._fin = weakref.finalize(
            self, _finalize_driver, self._L, self._h, self._cb
        )

    def stop(self) -> None:
        """Joins the workers (idempotent; the handle stays valid for metric
        reads). Shut the queue down first for a prompt join."""
        if self._h:
            self._L.kf_rd_stop(self._h)

    @property
    def total(self) -> int:
        return self._L.kf_rd_total(self._h) if self._h else 0

    @property
    def errors(self) -> int:
        return self._L.kf_rd_errors(self._h) if self._h else 0

    @property
    def conflicts(self) -> int:
        return self._L.kf_rd_conflicts(self._h) if self._h else 0

    def close(self) -> None:
        """Join + free now (equivalent to GC/exit finalization)."""
        if self._h:
            self._fin()
            self._h = None


def _take_string(ptr: int | None) -> str | None:
    """Copy a malloc'd C string and free it."""
    if not ptr:
        return None
    L = lib()
    s = ctypes.string_at(ptr).decode()
    L.kf_free(ptr)
    return s


class WorkQueue:
    """Rate-limited delaying work queue (client-go workqueue semantics)."""

    def __init__(self, base_delay_s: float = 0.005, max_delay_s: float = 60.0):
        self._L = lib()
        self._h = self._L.kf_wq_new(base_delay_s, max_delay_s)

    def add(self, key: str) -> None:
        self._L.kf_wq_add(self._h, key.encode())

    def add_after(self, key: str, delay_s: float) -> None:
        self._L.kf_wq_add_after(self._h, key.encode(), delay_s)

    def add_rate_limited(self, key: str) -> float:
        return self._L.kf_wq_add_rate_limited(self._h, key.encode())

    def forget(self, key: str) -> None:
        self._L.kf_wq_forget(self._h, key.encode())

    def num_requeues(self, key: str) -> int:
        return self._L.kf_wq_num_requeues(self._h, key.encode())

    def get(self, timeout_s: float = -1.0) -> str | None:
        return _take_string(self._L.kf_wq_get(self._h, timeout_s))

    def done(self, key: str) -> None:
        self._L.kf_wq_done(self._h, key.encode())

    def __len__(self) -> int:
        return self._L.kf_wq_len(self._h)

    def shutdown(self) -> None:
        self._L.kf_wq_shutdown(self._h)

    @property
    def shutting_down(self) -> bool:
        return bool(self._L.kf_wq_shutting_down(self._h))

    def close(self) -> None:
        if self._h:
            self._L.kf_wq_free(self._h)
            self._h = None


class Expectations:
    """ControllerExpectations: duplicate-action guard for reconcilers."""

    def __init__(self, ttl_s: float = 300.0):
        self._L = lib()
        self._h = self._L.kf_exp_new(ttl_s)

    def expect_creations(self, key: str, n: int) -> None:
        self._L.kf_exp_expect_creations(self._h, key.encode(), n)

    def expect_deletions(self, key: str, n: int) -> None:
        self._L.kf_exp_expect_deletions(self._h, key.encode(), n)

    def creation_observed(self, key: str) -> None:
        self._L.kf_exp_creation_observed(self._h, key.encode())

    def deletion_observed(self, key: str) -> None:
        self._L.kf_exp_deletion_observed(self._h, key.encode())

    def satisfied(self, key: str) -> bool:
        return bool(self._L.kf_exp_satisfied(self._h, key.encode()))

    def delete(self, key: str) -> None:
        self._L.kf_exp_delete(self._h, key.encode())

    def counts(self, key: str) -> tuple[int, int]:
        a = ctypes.c_longlong()
        d = ctypes.c_longlong()
        self._L.kf_exp_counts(self._h, key.encode(), ctypes.byref(a), ctypes.byref(d))
        return a.value, d.value

    def close(self) -> None:
        if self._h:
            self._L.kf_exp_free(self._h)
            self._h = None


class EventHub:
    """Broadcast hub with bounded per-subscriber buffers (informer fan-out).

    poll() returns (rc, seq, etype, kind, key): rc 0 = event, 1 = timeout,
    2 = subscriber overflowed (cleared — relist), 3 = unknown subscriber.
    """

    EVENT, EMPTY, OVERFLOWED, GONE = 0, 1, 2, 3

    def __init__(self, capacity: int = 4096):
        self._L = lib()
        self._h = self._L.kf_hub_new(capacity)
        self.capacity = capacity

    @staticmethod
    def _esc(s: str) -> str:
        """Escape the filter-spec/CSV metacharacters in a label key or
        value. Applied identically on the publish and subscribe sides, so
        the hub's equality match compares consistently-ENCODED strings —
        C++ never needs to decode, and a value like "x,app=b" can neither
        forge nor hide a selector match."""
        return (s.replace("%", "%25").replace(",", "%2C")
                .replace(";", "%3B").replace(":", "%3A")
                .replace("=", "%3D"))

    @classmethod
    def filter_spec(cls, filters) -> str:
        """Render {kind: selector | None} to the native filter string
        ("kind[:k[=v][,k2]];..."). selector = {label: value | None};
        a None value means "label present, any value"."""
        parts = []
        for kind, sel in filters.items():
            if sel:
                terms = ",".join(
                    cls._esc(k) if v is None
                    else f"{cls._esc(k)}={cls._esc(v)}"
                    for k, v in sorted(sel.items()))
                parts.append(f"{kind}:{terms}")
            else:
                parts.append(kind)
        return ";".join(parts)

    def subscribe(self, kinds=None, filters=None) -> int:
        """Subscribe; ``filters`` ({kind: label-selector-or-None}) or
        ``kinds`` (iterable — every kind unfiltered) installs a
        server-side filter: events outside it are never buffered for this
        subscriber, so they can neither overflow it nor cost it work."""
        if filters is None and kinds:
            filters = {k: None for k in kinds}
        if not filters:
            return self._L.kf_hub_subscribe(self._h)
        return self._L.kf_hub_subscribe_filtered(
            self._h, self.filter_spec(filters).encode())

    def unsubscribe(self, sub_id: int) -> None:
        self._L.kf_hub_unsubscribe(self._h, sub_id)

    def publish(self, etype: int, kind: str, key: str,
                labels: dict | None = None) -> int:
        if labels:
            csv = ",".join(f"{self._esc(k)}={self._esc(v)}"
                           for k, v in labels.items())
            return self._L.kf_hub_publish_labeled(
                self._h, etype, kind.encode(), key.encode(), csv.encode())
        return self._L.kf_hub_publish(self._h, etype, kind.encode(), key.encode())

    def poll(self, sub_id: int, timeout_s: float):
        seq = ctypes.c_longlong()
        etype = ctypes.c_int()
        kind = ctypes.c_void_p()
        key = ctypes.c_void_p()
        rc = self._L.kf_hub_poll(
            self._h, sub_id, timeout_s,
            ctypes.byref(seq), ctypes.byref(etype),
            ctypes.byref(kind), ctypes.byref(key),
        )
        if rc != 0:
            return rc, 0, 0, None, None
        return rc, seq.value, etype.value, _take_string(kind.value), _take_string(key.value)

    def backlog(self, sub_id: int) -> int:
        return self._L.kf_hub_backlog(self._h, sub_id)

    def close(self) -> None:
        if self._h:
            self._L.kf_hub_free(self._h)
            self._h = None

    def __del__(self):  # clusters are created per test; don't leak the hub
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


_FS, _RS = "\x1f", "\x1e"


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            i += 1
            out.append({"\\": "\\", "n": "\n", "f": _FS, "r": _RS}.get(s[i], s[i]))
        else:
            out.append(c)
        i += 1
    return "".join(out)


def _parse_records(raw: str | None, fields: list[str]) -> list[dict]:
    if not raw:
        return []
    out = []
    for rec in raw.split(_RS):
        vals = [_unescape(f) for f in rec.split(_FS)]
        if len(vals) == len(fields):
            out.append(dict(zip(fields, vals)))
    return out


_ARTIFACT_FIELDS = ["id", "type", "name", "uri", "props", "ts"]
_EXECUTION_FIELDS = ["id", "type", "name", "state", "props", "ts"]
_EVENT_FIELDS = ["execution_id", "artifact_id", "direction", "ts"]


class MetadataStore:
    """Lineage store (MLMD analogue): artifacts, executions, events."""

    INPUT, OUTPUT = 0, 1

    def __init__(self, path: str):
        self._L = lib()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._h = self._L.kf_ms_open(path.encode())

    def put_artifact(
        self, type: str, name: str, uri: str = "", props: str = "{}", id: int = 0
    ) -> int:
        return self._L.kf_ms_put_artifact(
            self._h, id, type.encode(), name.encode(), uri.encode(), props.encode()
        )

    def put_execution(
        self, type: str, name: str, state: str = "NEW", props: str = "{}", id: int = 0
    ) -> int:
        return self._L.kf_ms_put_execution(
            self._h, id, type.encode(), name.encode(), state.encode(), props.encode()
        )

    def put_event(self, execution_id: int, artifact_id: int, direction: int) -> None:
        rc = self._L.kf_ms_put_event(self._h, execution_id, artifact_id, direction)
        if rc != 0:
            raise KeyError(
                f"unknown execution {execution_id} or artifact {artifact_id}"
            )

    def get_artifact(self, id: int) -> dict | None:
        recs = _parse_records(
            _take_string(self._L.kf_ms_get_artifact(self._h, id)), _ARTIFACT_FIELDS
        )
        return recs[0] if recs else None

    def get_execution(self, id: int) -> dict | None:
        recs = _parse_records(
            _take_string(self._L.kf_ms_get_execution(self._h, id)), _EXECUTION_FIELDS
        )
        return recs[0] if recs else None

    def list_artifacts(self, type: str = "") -> list[dict]:
        return _parse_records(
            _take_string(self._L.kf_ms_list_artifacts(self._h, type.encode())),
            _ARTIFACT_FIELDS,
        )

    def list_executions(self, type: str = "") -> list[dict]:
        return _parse_records(
            _take_string(self._L.kf_ms_list_executions(self._h, type.encode())),
            _EXECUTION_FIELDS,
        )

    def events(self, execution_id: int = 0, artifact_id: int = 0) -> list[dict]:
        return _parse_records(
            _take_string(self._L.kf_ms_events(self._h, execution_id, artifact_id)),
            _EVENT_FIELDS,
        )

    def close(self) -> None:
        if self._h:
            self._L.kf_ms_close(self._h)
            self._h = None
