"""Rendezvous registry — headless-Service DNS for local processes.

The env contract names replicas by stable DNS-style hostnames
(`{job}-{rtype}-{i}.{job}.{ns}`). On a real cluster those resolve via
headless Services; locally we rewrite them to 127.0.0.1 with per-job unique
ports so `jax.distributed.initialize` and friends connect for real.
"""

from __future__ import annotations

import socket
from dataclasses import dataclass, field

from kubeflow_tpu.api.jobs import TrainJob


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class LocalResolver:
    """Maps replica hostnames to loopback endpoints for one job."""

    job: TrainJob
    port_map: dict[str, int] = field(default_factory=dict)

    def endpoint(self, rtype: str, index: int) -> str:
        host = self.job.replica_hostname(rtype, index)
        if host not in self.port_map:
            self.port_map[host] = free_port()
        return f"127.0.0.1:{self.port_map[host]}"

    def rewrite_env(self, env: dict[str, str]) -> dict[str, str]:
        """Replace every known hostname[:anyport] in env values with loopback.

        A `host:port` occurrence maps to that host's unique loopback port
        (whatever framework port the contract used — 2222, 23456, ...), so
        per-replica endpoints stay distinct locally; a bare hostname maps to
        127.0.0.1.
        """
        import re

        for rtype, rs in self.job.spec.replica_specs.items():
            for i in range(rs.replicas):
                self.endpoint(rtype, i)
        # Longest-first + boundary lookahead so 'job-worker-1' never rewrites
        # the prefix of 'job-worker-10' (hostname chars are [A-Za-z0-9.-]).
        hosts = sorted(self.port_map, key=len, reverse=True)
        out = {}
        for k, v in env.items():
            for host in hosts:
                port = self.port_map[host]
                v = re.sub(
                    rf"{re.escape(host)}:\d+", f"127.0.0.1:{port}", v
                )
                v = re.sub(
                    rf"{re.escape(host)}(?![A-Za-z0-9.-])", "127.0.0.1", v
                )
            out[k] = v
        return out
