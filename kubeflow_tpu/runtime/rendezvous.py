"""Rendezvous registry — headless-Service DNS for local processes.

The env contract names replicas by stable DNS-style hostnames
(`{job}-{rtype}-{i}.{job}.{ns}`). On a real cluster those resolve via
headless Services; locally we rewrite them to 127.0.0.1 with per-job unique
ports so `jax.distributed.initialize` and friends connect for real.
"""

from __future__ import annotations

import socket
from dataclasses import dataclass, field

from kubeflow_tpu.api.jobs import TrainJob


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class LocalResolver:
    """Maps replica hostnames to loopback endpoints for one job."""

    job: TrainJob
    port_map: dict[str, int] = field(default_factory=dict)

    def endpoint(self, rtype: str, index: int) -> str:
        host = self.job.replica_hostname(rtype, index)
        if host not in self.port_map:
            self.port_map[host] = free_port()
        return f"127.0.0.1:{self.port_map[host]}"

    def _hosts(self) -> list[str]:
        """Populate the port map for every replica, longest hostname first
        (so 'job-worker-1' never rewrites the prefix of 'job-worker-10')."""
        for rtype, rs in self.job.spec.replica_specs.items():
            for i in range(rs.replicas):
                self.endpoint(rtype, i)
        return sorted(self.port_map, key=len, reverse=True)

    def _rewrite(self, text: str, hosts: list[str]) -> str:
        import re

        for host in hosts:
            port = self.port_map[host]
            text = re.sub(rf"{re.escape(host)}:\d+", f"127.0.0.1:{port}", text)
            text = re.sub(rf"{re.escape(host)}(?![A-Za-z0-9.-])", "127.0.0.1", text)
        return text

    def rewrite_text(self, text: str) -> str:
        """Replace every known hostname[:anyport] with loopback.

        A `host:port` occurrence maps to that host's unique loopback port
        (whatever framework port the contract used — 2222, 23456, ...), so
        per-replica endpoints stay distinct locally; a bare hostname maps to
        127.0.0.1. Used for env values and for materialized files (the MPI
        hostfile).
        """
        return self._rewrite(text, self._hosts())

    def rewrite_env(self, env: dict[str, str]) -> dict[str, str]:
        hosts = self._hosts()
        return {k: self._rewrite(v, hosts) for k, v in env.items()}
