"""Worker-side distributed bootstrap — the consumer of the L3 env contract.

The controller synthesizes JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID (controller/envcontract.py#jax_env); this module is the other
half: a worker process calls `initialize_from_env()` first thing, which wires
`jax.distributed.initialize` (the gRPC coordination service built into
jaxlib — the TPU-native replacement for the reference's c10d/NCCL rendezvous,
SURVEY.md §2.3) and returns the process topology.

Works identically on: real multi-host TPU slices (env comes from GKE), local
multi-process CPU gangs (env comes from the fake cluster's LocalResolver),
and single-process runs (no env -> no-op).
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class DistContext:
    process_id: int
    num_processes: int
    coordinator: str | None
    # multislice topology (MEGASCALE_* contract, SURVEY.md §2.3): slices are
    # the DCN-connected units; processes within a slice share ICI
    num_slices: int = 1
    slice_id: int = 0

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @property
    def is_multislice(self) -> bool:
        return self.num_slices > 1

    @property
    def processes_per_slice(self) -> int:
        return self.num_processes // max(self.num_slices, 1)


def initialize_from_env(
    platform: str | None = None, local_device_count: int | None = None
) -> DistContext:
    """Initialize jax.distributed from the JAXJob env contract.

    platform: force a jax platform ("cpu" for local gangs — two processes
    cannot share the one axon TPU chip). local_device_count: virtual CPU
    devices this process contributes (overrides any inherited XLA_FLAGS —
    pod processes inherit the parent env, which may carry a test harness's
    device-count flag). Must run before any other jax use.
    """
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    n = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
    # liveness: announce this incarnation BEFORE anything that can wedge
    # (jax import, distributed rendezvous) — a worker stuck right here is
    # exactly the hang the lease detector exists for (docs/health.md)
    from kubeflow_tpu.health import HeartbeatWriter

    hb = HeartbeatWriter.from_env()
    if hb is not None:
        hb.beat(step=-1, phase="rendezvous")
    # multislice contract: on real Cloud TPU these are consumed by libtpu's
    # megascale transport; here they carry the slice topology into the mesh
    # builder (slice-major device order => data-like axes ride DCN)
    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    slice_id = int(os.environ.get("MEGASCALE_SLICE_ID", "0"))
    if num_slices > 1:
        if n % num_slices:
            raise ValueError(
                f"JAX_NUM_PROCESSES {n} not divisible by "
                f"MEGASCALE_NUM_SLICES {num_slices}"
            )
        expect = pid // (n // num_slices)
        if slice_id != expect:
            raise ValueError(
                f"MEGASCALE_SLICE_ID {slice_id} inconsistent with process "
                f"{pid}/{n} over {num_slices} slices (expected {expect})"
            )

    if local_device_count is not None:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={local_device_count}"
        ).strip()

    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if coord and n > 1:
        # the gang's rendezvous is the canonical recovery-path span: a
        # restarted gang's wall-clock between rebind and first step is
        # mostly spent right here. No-op unless the pod env carries
        # KFTPU_TRACE_DIR (tracing.init_worker_from_env).
        from kubeflow_tpu.tracing import init_worker_from_env

        tracer = init_worker_from_env(service="worker")
        with tracer.span("rendezvous", coordinator=coord, world=n, rank=pid):
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=n, process_id=pid
            )
    if hb is not None:
        # the gang is formed: subsequent beats come from the training loop
        hb.beat(step=-1, phase="rendezvous-done")
    return DistContext(
        process_id=pid, num_processes=n, coordinator=coord,
        num_slices=num_slices, slice_id=slice_id,
    )


def shutdown() -> None:
    import jax

    if jax.process_count() > 1:
        jax.distributed.shutdown()
