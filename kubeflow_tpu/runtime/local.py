"""LocalRunner: run a job's replicas as real subprocesses.

P1 scope (SURVEY.md §7): launch, env-inject, wait, verdict. Gang semantics,
restart policies, and the reconcile loop live in the controller (P2) — the
runner is the kubelet, not the scheduler.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path

from kubeflow_tpu.api.common import JobConditionType
from kubeflow_tpu.api.jobs import SUCCESS_REPLICA, TrainJob, REPLICA_CHIEF, REPLICA_WORKER
from kubeflow_tpu.api.validation import validate_job
from kubeflow_tpu.controller.envcontract import synthesize_env
from kubeflow_tpu.runtime.rendezvous import LocalResolver
from kubeflow_tpu.utils.retry import Deadline


@dataclass
class ReplicaResult:
    rtype: str
    index: int
    exit_code: int
    log_path: str
    duration_s: float


@dataclass
class JobResult:
    succeeded: bool
    replicas: list[ReplicaResult] = field(default_factory=list)

    def logs(self, rtype: str = REPLICA_WORKER, index: int = 0) -> str:
        for r in self.replicas:
            if r.rtype == rtype and r.index == index:
                return Path(r.log_path).read_text()
        raise KeyError(f"{rtype}-{index}")


class LocalRunner:
    """Runs every replica of a (validated) job as a local subprocess."""

    def __init__(self, log_dir: str | None = None, inherit_env: bool = True):
        self.log_dir = Path(log_dir or ".kubeflow_tpu/logs")
        self.inherit_env = inherit_env

    def run(self, job: TrainJob, timeout: float | None = None) -> JobResult:
        validate_job(job)
        # reject unlaunchable specs before spawning anything (no orphan leak)
        for rtype, rs in job.spec.replica_specs.items():
            if rs.replicas > 0 and not (
                rs.template.container.command or rs.template.container.args
            ):
                raise ValueError(f"replica {rtype} has no command")
        resolver = LocalResolver(job)
        self.log_dir.mkdir(parents=True, exist_ok=True)

        procs: list[tuple[str, int, subprocess.Popen, str, float]] = []
        for rtype, rs in job.spec.replica_specs.items():
            for i in range(rs.replicas):
                c = rs.template.container
                cmd = list(c.command) + list(c.args)
                env = dict(os.environ) if self.inherit_env else {}
                env.update(resolver.rewrite_env(synthesize_env(job, rtype, i)))
                log_path = str(self.log_dir / f"{job.replica_name(rtype, i)}.log")
                with open(log_path, "wb") as logf:  # child dups the fd
                    proc = subprocess.Popen(
                        cmd,
                        env=env,
                        stdout=logf,
                        stderr=subprocess.STDOUT,
                        cwd=c.working_dir or None,
                    )
                procs.append((rtype, i, proc, log_path, time.monotonic()))

        # one shared deadline for the whole gang (utils/retry.Deadline):
        # explicit timeout wins, else runPolicy.activeDeadlineSeconds
        deadline = Deadline(
            timeout if timeout is not None
            else job.spec.run_policy.active_deadline_seconds or None
        )
        results: list[ReplicaResult] = []
        for rtype, i, proc, log_path, t0 in procs:
            remaining = deadline.remaining(floor=0.1)
            try:
                code = proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                proc.kill()
                code = proc.wait()
            results.append(
                ReplicaResult(rtype, i, code, log_path, time.monotonic() - t0)
            )

        success_rtype = SUCCESS_REPLICA[job.kind]
        rs = job.spec.replica_specs.get(success_rtype)
        if rs is None or rs.replicas == 0:
            # TFJob chief fallback, master fallback: worker-0 decides
            success_rtype = REPLICA_WORKER
        deciders = [
            r for r in results
            if r.rtype == success_rtype and (r.index == 0 or r.rtype == REPLICA_WORKER)
        ]
        verdict = bool(deciders) and all(r.exit_code == 0 for r in deciders)
        if verdict and job.spec.success_policy == "AllWorkers":
            # same verdict the controller reaches for this spec: every
            # worker must complete cleanly, not just the decider
            workers = [r for r in results if r.rtype == REPLICA_WORKER]
            verdict = all(r.exit_code == 0 for r in workers)

        st = job.status
        st.start_time = st.start_time or _now()
        if verdict:
            st.set_condition(JobConditionType.SUCCEEDED, "JobSucceeded")
        else:
            st.set_condition(JobConditionType.FAILED, "JobFailed")
        st.completion_time = _now()
        return JobResult(succeeded=verdict, replicas=results)


def _now() -> str:
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
