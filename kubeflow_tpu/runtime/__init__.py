"""Runtime layer: turning specs into real OS processes.

The 'kubelet' of this framework. A replica 'pod' is a subprocess; the
rendezvous registry is the headless-Service DNS analogue (SURVEY.md §7 P2:
the cluster is a fake-cluster runtime launching real local processes,
mirroring how the reference tests itself via envtest without clusters).
"""

from kubeflow_tpu.runtime.local import LocalRunner, ReplicaResult

__all__ = ["LocalRunner", "ReplicaResult"]
