"""InferenceServiceController — predictor replica management + readiness.

Reference parity (unverified cites, SURVEY.md §2.5): kserve
pkg/controller/v1beta1/inferenceservice in RawDeployment mode: reconcile the
ISVC into predictor replicas, surface readiness + URL in status, self-heal
dead replicas. The SERVERLESS mode is covered too, without the
Istio/Knative stack (that stack is out of scope per SURVEY.md §7, its
semantics are not): minReplicas=0 reaps the last replica after
scaleToZeroGraceS of idle, and serving/activator.py is the front door
that holds requests through the cold start and stamps the demand
annotation this controller wakes on.

Each replica is a pod running `python -m kubeflow_tpu.serving.server`; the
replica's port is allocated at pod-creation time and recorded in a pod
annotation (the Service/Endpoint analogue the client reads).
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import (
    FakeCluster,
    Pod,
    PodPhase,
)
from kubeflow_tpu.runtime.rendezvous import free_port
from kubeflow_tpu.serving.api import InferenceService, PredictorRuntime
import kubeflow_tpu

# the server subprocess must be able to import this package regardless of
# the parent's cwd
_PKG_ROOT = str(Path(kubeflow_tpu.__file__).resolve().parent.parent)

ISVC_LABEL = "kubeflow-tpu.org/inferenceservice"
PORT_ANNOTATION = "kubeflow-tpu.org/serving-port"
GRPC_PORT_ANNOTATION = "kubeflow-tpu.org/serving-grpc-port"
REPLICA_INDEX_LABEL = "kubeflow-tpu.org/replica-index"
CANARY_LABEL = "kubeflow-tpu.org/canary"
SPEC_HASH_ANNOTATION = "kubeflow-tpu.org/predictor-spec-hash"


def _spec_hash(predictor, transformer, explainer=None) -> str:
    """Fingerprint of everything a replica's command/env derives from; a
    changed spec rolls the replica (the Deployment-template-hash analogue)."""
    import hashlib

    from kubeflow_tpu.api.serde import to_dict

    p = to_dict(predictor)
    # replica COUNT shapes the set, not any one pod — autoscaling must not
    # roll every replica on each scale decision
    p.pop("replicas", None)
    blob = json.dumps(
        {"p": p, "t": to_dict(transformer) if transformer else None,
         "e": to_dict(explainer) if explainer else None},
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def probe_ready(url: str, timeout_s: float = 0.5) -> bool:
    try:
        with urllib.request.urlopen(f"{url}/v2/health/ready", timeout=timeout_s) as r:
            return json.loads(r.read()).get("ready", False)
    except Exception:  # noqa: BLE001 — any failure = not ready
        return False


class InferenceServiceController(ControllerBase):
    WATCH_SELECTORS = {"inferenceservices": None,
                       "pods": {ISVC_LABEL: None}}
    ERROR_EVENT_KIND = "inferenceservices"

    def __init__(self, cluster: FakeCluster, workers: int = 1,
                 resync_period_s: float = 1.0, model_cache_dir: str = ".kubeflow_tpu/model-cache",
                 platform=None):
        # readiness probing rides the resync cadence
        super().__init__(
            cluster, name="isvc", workers=workers,
            resync_period_s=resync_period_s,
            wq_base_delay_s=0.01, wq_max_delay_s=5.0,
        )
        self.model_cache_dir = model_cache_dir
        #: back-reference for the fleet-demand autoscale path: an ISVC
        #: whose fleet is registered (Platform.register_fleet under the
        #: same "ns/name" key) scales from FleetRouter.demand_replicas_
        #: burn instead of the request-rate estimate (docs/autoscaling.md)
        self.platform = platform
        # probes are blocking HTTP calls: run them off a pool so one slow
        # replica can't serialize readiness detection for everything else
        self._probe_pool = ThreadPoolExecutor(max_workers=8,
                                              thread_name_prefix="isvc-probe")
        self._seen: set[str] = set()
        # key -> (monotonic time, {endpoint url -> request total}); per-URL
        # so a restarted replica's counter reset never reads as load collapse
        self._qps_samples: dict[str, tuple[float, dict[str, int]]] = {}
        # key -> monotonic time of the last nonzero-qps observation
        # (drives the scale-to-zero idle grace window)
        self._last_traffic: dict[str, float] = {}
        self.metrics.update({
            "isvc_created_total": 0,
            "isvc_ready_total": 0,
            "predictor_pods_created_total": 0,
            "predictor_pods_restarted_total": 0,
        })

    def stop(self) -> None:
        super().stop()
        self._probe_pool.shutdown(wait=False)

    # -------------------------------------------------------------- informer

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == "inferenceservices":
            return self.cluster._key(obj)
        if kind == "pods":
            name = obj.metadata.labels.get(ISVC_LABEL)
            if name:
                return f"{obj.metadata.namespace}/{name}"
        return None

    def resync_keys(self):
        return [self.cluster._key(i) for i in self.cluster.list("inferenceservices")]

    # ------------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> float | None:
        isvc: InferenceService | None = self.cluster.get(
            "inferenceservices", key, copy_obj=True
        )
        if isvc is None:
            # cascade: a deleted service must not leave server processes
            # behind (e.g. self-heal recreated a pod mid-deletion)
            ns, _, name = key.partition("/")
            for p in self.cluster.list(
                "pods",
                lambda p: p.metadata.labels.get(ISVC_LABEL) == name
                and p.metadata.namespace == ns,
            ):
                self.cluster.delete("pods", p.key)
            self._seen.discard(key)
            self._qps_samples.pop(key, None)
            self._last_traffic.pop(key, None)
            return None
        if key not in self._seen:
            self._seen.add(key)
            self.metrics["isvc_created_total"] += 1

        created, endpoints = self._reconcile_replica_set(
            isvc, key, isvc.spec.predictor, canary=False
        )
        if isvc.spec.canary is not None:
            c_created, c_endpoints = self._reconcile_replica_set(
                isvc, key, isvc.spec.canary, canary=True
            )
        else:
            c_created, c_endpoints = 0, []
            # promotion/rollback removed the canary: reap its pods — but only
            # once the primary serves again (a promotion rolls the primary to
            # the new spec; the canary bridges that window)
            if any(e.ready for e in endpoints):
                for p in self._owned_pods(isvc):
                    if p.metadata.labels.get(CANARY_LABEL) == "true":
                        self.cluster.delete("pods", p.key)
        created += c_created

        st = isvc.status
        before = (st.ready, st.replicas_ready, st.url, st.canary_ready,
                  tuple((e.url, e.ready) for e in st.endpoints),
                  tuple((e.url, e.ready) for e in st.canary_endpoints))
        st.endpoints = endpoints
        st.replicas_ready = sum(1 for e in endpoints if e.ready)
        st.canary_endpoints = c_endpoints
        st.canary_ready = sum(1 for e in c_endpoints if e.ready)
        newly_ready = st.replicas_ready > 0 and not st.ready
        st.ready = st.replicas_ready > 0
        ready_eps = [e for e in endpoints if e.ready]
        st.url = ready_eps[0].url if ready_eps else ""
        after = (st.ready, st.replicas_ready, st.url, st.canary_ready,
                 tuple((e.url, e.ready) for e in st.endpoints),
                 tuple((e.url, e.ready) for e in st.canary_endpoints))
        if before != after:
            self.cluster.update("inferenceservices", isvc)
            if newly_ready:
                self.metrics["isvc_ready_total"] += 1
                self.cluster.record_event(
                    "inferenceservices", key, "Ready",
                    f"{st.replicas_ready}/{isvc.spec.predictor.replicas} "
                    f"replicas ready at {st.url}",
                )

        self._autoscale(isvc, key, endpoints)

        # keep probing until the full replica sets are ready
        want_canary = isvc.spec.canary.replicas if isvc.spec.canary else 0
        if (created or st.replicas_ready < isvc.spec.predictor.replicas
                or st.canary_ready < want_canary):
            return 0.3
        return None

    def _reconcile_replica_set(self, isvc: InferenceService, key: str,
                               predictor, canary: bool):
        """Self-heal + spec-hash roll + scale one replica set; returns
        (created_count, probed endpoints)."""
        flag = "true" if canary else ""
        want_hash = _spec_hash(predictor, isvc.spec.transformer,
                                isvc.spec.explainer)
        pods = [
            p for p in self._owned_pods(isvc)
            if p.metadata.labels.get(CANARY_LABEL, "") == flag
        ]
        deleted: set[str] = set()
        rolled = False
        for p in pods:
            if p.status.phase in (PodPhase.FAILED, PodPhase.SUCCEEDED):
                # self-heal: serving replicas must always run; any exited
                # replica (crash OR clean exit) is replaced
                self.cluster.delete("pods", p.key)
                deleted.add(p.key)
                self.metrics["predictor_pods_restarted_total"] += 1
                self.cluster.record_event(
                    "inferenceservices", key, "PredictorRestarted",
                    f"replica {p.metadata.name} exited "
                    f"(code {p.status.exit_code}); recreating",
                    type="Warning",
                )
            elif (
                not rolled
                and p.metadata.annotations.get(SPEC_HASH_ANNOTATION) != want_hash
            ):
                # rolling update: the spec this pod was built from changed
                # (e.g. canary promotion). AT MOST ONE stale pod per pass so
                # a multi-replica set keeps serving through the roll.
                rolled = True
                self.cluster.delete("pods", p.key)
                deleted.add(p.key)
                self.cluster.record_event(
                    "inferenceservices", key, "PredictorRolled",
                    f"replica {p.metadata.name} restarted for spec change",
                )
        pods = [
            p for p in pods
            if p.key not in deleted
            and p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        ]

        have = {int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)) for p in pods}
        created = 0
        for i in range(predictor.replicas):
            if i not in have:
                self._create_replica(isvc, i, predictor, canary=canary)
                created += 1
        # drop excess replicas after a scale-down (highest index first)
        for p in sorted(
            pods,
            key=lambda p: int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)),
            reverse=True,
        ):
            if int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)) >= predictor.replicas:
                self.cluster.delete("pods", p.key)
                deleted.add(p.key)
        if created or deleted:
            pods = [
                p for p in self._owned_pods(isvc)
                if p.metadata.labels.get(CANARY_LABEL, "") == flag
            ]

        # probe readiness per running replica (concurrently: each probe can
        # block up to its timeout)
        from kubeflow_tpu.serving.api import ReplicaEndpoint

        ordered = sorted(
            pods, key=lambda p: int(p.metadata.labels.get(REPLICA_INDEX_LABEL, 0))
        )
        urls = [
            f"http://127.0.0.1:{p.metadata.annotations.get(PORT_ANNOTATION, '')}"
            if p.metadata.annotations.get(PORT_ANNOTATION) else ""
            for p in ordered
        ]
        futures = [
            self._probe_pool.submit(probe_ready, url)
            if (p.status.phase == PodPhase.RUNNING and url) else None
            for p, url in zip(ordered, urls)
        ]
        endpoints = [
            ReplicaEndpoint(url=url, ready=(f is not None and f.result()))
            for url, f in zip(urls, futures)
        ]
        # an in-progress roll counts as pending work (requeue until done)
        return created + (1 if rolled else 0), endpoints

    # ------------------------------------------------------------ autoscale

    def _autoscale(self, isvc: InferenceService, key: str, endpoints) -> None:
        """HPA analogue: size the primary replica set to the observed request
        rate (kfserving_requests_total deltas from each ready replica's
        /metrics), clamped to [min, max], one decision per scale interval.
        minReplicas=0 adds the serverless pair: scale-from-zero when the
        activator stamps fresh demand, scale-TO-zero after the idle grace
        window (Knative autoscaler analogue)."""
        a = isvc.spec.autoscaling
        if a is None:
            return
        import math
        import re
        import time

        now = time.monotonic()

        if isvc.spec.predictor.replicas == 0:
            # scaled to zero: the only wake signal is activator demand
            # (no replicas -> no counters to sample); must not sit behind
            # the decision cooldown — activation latency IS the product
            from kubeflow_tpu.serving.activator import DEMAND_ANNOTATION

            stamp = isvc.metadata.annotations.get(DEMAND_ANNOTATION, "")
            try:
                fresh = (time.time() - float(stamp)) < a.scale_to_zero_grace_s
            except ValueError:
                fresh = False
            if fresh:
                self._scale_to(isvc, key, a, max(a.min_replicas, 1),
                               reason="activator demand")
                self._last_traffic[key] = now
            return

        # fleet-demand path (docs/autoscaling.md): when this service's
        # FleetRouter is registered on the platform, the burn-rate-aware
        # demand signal replaces the request-rate estimate — the signal
        # already folds queue depth, service rate, AND the SLO burn
        # (demand_replicas_burn), so the HPA math below would be a
        # worse duplicate of it
        fleet = (getattr(self.platform, "fleet_routers", {}) or {}) \
            .get(key) if self.platform is not None else None
        if fleet is not None:
            self._autoscale_fleet(isvc, key, a, fleet, now)
            return

        prev = self._qps_samples.get(key)
        if prev is not None and now - prev[0] < a.scale_interval_s:
            return  # inside the decision window: no sampling, no blocking IO

        def fetch(url: str) -> tuple[str, int] | None:
            try:
                with urllib.request.urlopen(f"{url}/metrics", timeout=0.5) as r:
                    text = r.read().decode()
                return url, sum(
                    int(m) for m in re.findall(
                        r"^kfserving_requests_total\{[^}]*\} (\d+)$",
                        text, re.MULTILINE,
                    )
                )
            except Exception:  # noqa: BLE001 — a dead replica samples as absent
                return None

        futures = [
            self._probe_pool.submit(fetch, e.url) for e in endpoints if e.ready
        ]
        counts = dict(f.result() for f in futures if f.result() is not None)
        if not counts:
            return
        self._qps_samples[key] = (now, counts)
        if prev is None:
            # first sample for this (possibly restarted) controller: a
            # nonzero counter is traffic accrued since pod start — it must
            # refresh the idle clock, or a cold start longer than the
            # grace window would reap the replica right after it serves
            # the request that woke it
            if sum(counts.values()) > 0:
                self._last_traffic[key] = now
            return
        t0, counts0 = prev
        dt = max(now - t0, 1e-6)
        # per-URL deltas: a restarted replica's counter reset (or a scaled-
        # down replica vanishing) must never read as a load collapse; a
        # fresh URL's full count accrued within the window
        delta = sum(
            max(c - counts0.get(url, 0), 0) for url, c in counts.items()
        )
        qps = delta / dt
        if qps > 0 or key not in self._last_traffic:
            self._last_traffic[key] = now
        floor = a.min_replicas
        if floor == 0:
            # serverless: hold one replica while traffic is recent; reap
            # the last replica only after the idle grace window
            idle_s = now - self._last_traffic[key]
            floor = 0 if idle_s >= a.scale_to_zero_grace_s else 1
        desired = int(
            min(max(math.ceil(qps / a.target_qps_per_replica), floor),
                a.max_replicas)
        )
        if desired == isvc.spec.predictor.replicas:
            return
        reason = (f"observed {qps:.1f} qps, "
                  f"target {a.target_qps_per_replica}/replica"
                  if desired else
                  f"idle {now - self._last_traffic[key]:.0f}s >= "
                  f"scaleToZeroGraceS {a.scale_to_zero_grace_s:.0f}s")
        self._scale_to(isvc, key, a, desired, reason=reason)

    def _autoscale_fleet(self, isvc: InferenceService, key: str, a,
                         fleet, now: float) -> None:
        """Demand-signal replica decision: desired count straight from
        the fleet's burn-aware demand (the FleetScaler consumes the same
        signal in-process; here it sizes the ISVC's replica SET), one
        decision per scale interval, scale-to-zero only after the idle
        grace window — the serverless semantics of the qps path kept."""
        prev = self._qps_samples.get(key)
        if prev is not None and now - prev[0] < a.scale_interval_s:
            return
        self._qps_samples[key] = (now, {})
        monitor = getattr(self.platform, "slo_monitor", None)
        demand = (fleet.demand_replicas_burn(monitor)
                  if monitor is not None else fleet.demand_replicas())
        self._last_traffic.setdefault(key, now)
        # demand_replicas floors at 1 while ANY replica serves (its own
        # scale-in floor, by design) — so a floor-1 reading is NOT
        # traffic; only demand past the floor or actual queued work
        # refreshes the idle clock, or scaleToZeroGraceS could never
        # elapse and the serverless contract would be silently dead
        if demand > 1 or fleet.queue_depth() > 0:
            self._last_traffic[key] = now
        floor = a.min_replicas
        idle = False
        if floor == 0:
            idle = (now - self._last_traffic[key]
                    >= a.scale_to_zero_grace_s)
            floor = 0 if idle else 1
        if idle and fleet.queue_depth() == 0:
            # idle past the grace with nothing queued: override the
            # signal's alive-floor of 1 and reap to zero
            desired = 0
        else:
            desired = int(min(max(demand, floor), a.max_replicas))
        if desired == isvc.spec.predictor.replicas:
            return
        self._scale_to(
            isvc, key, a, desired,
            reason=f"fleet demand {demand} "
                   f"({'burn-aware' if monitor is not None else 'queue'})")

    def _scale_to(self, isvc: InferenceService, key: str, a, desired: int,
                  reason: str) -> None:
        cur = self.cluster.get("inferenceservices", key, copy_obj=True)
        if (cur is None or cur.spec.autoscaling is None
                or cur.spec.predictor.replicas == desired):
            return
        cur.spec.predictor.replicas = desired
        try:
            self.cluster.update("inferenceservices", cur)
        except Exception:  # noqa: BLE001 — conflict: next resync re-decides
            return
        self.cluster.record_event(
            "inferenceservices", key, "Autoscaled",
            f"replicas -> {desired} ({reason})",
        )

    # ------------------------------------------------------------- sub-steps

    def _owned_pods(self, isvc: InferenceService) -> list[Pod]:
        return self.cluster.list(
            "pods",
            lambda p: p.metadata.labels.get(ISVC_LABEL) == isvc.metadata.name
            and p.metadata.namespace == isvc.metadata.namespace,
        )

    def _create_replica(self, isvc: InferenceService, index: int,
                        predictor=None, canary: bool = False) -> None:
        p = predictor if predictor is not None else isvc.spec.predictor
        kind = "canary" if canary else "predictor"
        port = free_port()
        cmd = [
            sys.executable, "-m", "kubeflow_tpu.serving.server",
            "--model-name", isvc.metadata.name,
            "--runtime", p.runtime.value,
            "--port", str(port),
            # per-replica dir: concurrent replicas pulling the same model
            # must not clobber each other's files mid-load
            "--model-dir",
            f"{self.model_cache_dir}/{isvc.metadata.namespace}/{kind}-r{index}",
        ]
        if p.storage_uri:
            cmd += ["--storage-uri", p.storage_uri]
        if p.model_class:
            cmd += ["--model-class", p.model_class]
        if p.device:
            cmd += ["--device", p.device]
        if getattr(p, "aot", False):
            cmd += ["--aot"]
        if p.max_batch_size > 0:
            # agent micro-batching: concurrent requests coalesce into one
            # forward pass up to this many rows (serving/agent.py)
            cmd += ["--max-batch-size", str(p.max_batch_size)]
        grpc_port = None
        if getattr(p, "grpc", False):
            # controller-assigned (like the HTTP port) so the address is
            # known up front and annotated on the pod
            grpc_port = free_port()
            cmd += ["--grpc-port", str(grpc_port)]
        if isvc.spec.transformer is not None:
            cmd += ["--transformer-class", isvc.spec.transformer.model_class]
        if isvc.spec.explainer is not None:
            cmd += ["--explainer-class", isvc.spec.explainer.model_class]
        env = dict(p.env)
        # transformer/explainer hops run in the same server process: their
        # env merges in (predictor keys win on collision)
        if isvc.spec.explainer is not None:
            env = {**isvc.spec.explainer.env, **env}
        if isvc.spec.transformer is not None:
            env = {**isvc.spec.transformer.env, **env}
        env["PYTHONPATH"] = _PKG_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else (os.pathsep + os.environ["PYTHONPATH"] if os.environ.get("PYTHONPATH") else "")
        )
        labels = {
            ISVC_LABEL: isvc.metadata.name,
            REPLICA_INDEX_LABEL: str(index),
        }
        if canary:
            labels[CANARY_LABEL] = "true"
        pod = Pod(
            metadata=ObjectMeta(
                name=f"{isvc.metadata.name}-{kind}-{index}",
                namespace=isvc.metadata.namespace,
                labels=labels,
                annotations={
                    PORT_ANNOTATION: str(port),
                    **({GRPC_PORT_ANNOTATION: str(grpc_port)}
                       if grpc_port is not None else {}),
                    SPEC_HASH_ANNOTATION: _spec_hash(
                        p, isvc.spec.transformer, isvc.spec.explainer
                    ),
                },
            ),
            command=cmd,
            env=env,
            scheduler_name="default",  # serving pods bypass gang scheduling
        )
        from kubeflow_tpu.controller.poddefault import apply_pod_defaults

        apply_pod_defaults(self.cluster, pod)  # admission mutation
        try:
            self.cluster.create("pods", pod)
        except KeyError:
            return  # replaced concurrently
        self.metrics["predictor_pods_created_total"] += 1
