"""InferenceServiceController — predictor replica management + readiness.

Reference parity (unverified cites, SURVEY.md §2.5): kserve
pkg/controller/v1beta1/inferenceservice in RawDeployment mode: reconcile the
ISVC into predictor replicas, surface readiness + URL in status, self-heal
dead replicas. Serverless (Knative activator / scale-to-zero) is out of
scope by design (SURVEY.md §7).

Each replica is a pod running `python -m kubeflow_tpu.serving.server`; the
replica's port is allocated at pod-creation time and recorded in a pod
annotation (the Service/Endpoint analogue the client reads).
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.controller.base import ControllerBase
from kubeflow_tpu.controller.fakecluster import (
    FakeCluster,
    Pod,
    PodPhase,
)
from kubeflow_tpu.runtime.rendezvous import free_port
from kubeflow_tpu.serving.api import InferenceService, PredictorRuntime
import kubeflow_tpu

# the server subprocess must be able to import this package regardless of
# the parent's cwd
_PKG_ROOT = str(Path(kubeflow_tpu.__file__).resolve().parent.parent)

ISVC_LABEL = "kubeflow-tpu.org/inferenceservice"
PORT_ANNOTATION = "kubeflow-tpu.org/serving-port"
REPLICA_INDEX_LABEL = "kubeflow-tpu.org/replica-index"


def probe_ready(url: str, timeout_s: float = 0.5) -> bool:
    try:
        with urllib.request.urlopen(f"{url}/v2/health/ready", timeout=timeout_s) as r:
            return json.loads(r.read()).get("ready", False)
    except Exception:  # noqa: BLE001 — any failure = not ready
        return False


class InferenceServiceController(ControllerBase):
    ERROR_EVENT_KIND = "inferenceservices"

    def __init__(self, cluster: FakeCluster, workers: int = 1,
                 resync_period_s: float = 1.0, model_cache_dir: str = ".kubeflow_tpu/model-cache"):
        # readiness probing rides the resync cadence
        super().__init__(
            cluster, name="isvc", workers=workers,
            resync_period_s=resync_period_s,
            wq_base_delay_s=0.01, wq_max_delay_s=5.0,
        )
        self.model_cache_dir = model_cache_dir
        # probes are blocking HTTP calls: run them off a pool so one slow
        # replica can't serialize readiness detection for everything else
        self._probe_pool = ThreadPoolExecutor(max_workers=8,
                                              thread_name_prefix="isvc-probe")
        self._seen: set[str] = set()
        self.metrics.update({
            "isvc_created_total": 0,
            "isvc_ready_total": 0,
            "predictor_pods_created_total": 0,
            "predictor_pods_restarted_total": 0,
        })

    def stop(self) -> None:
        super().stop()
        self._probe_pool.shutdown(wait=False)

    # -------------------------------------------------------------- informer

    def kind_filter(self, etype, kind: str, obj) -> str | None:
        if kind == "inferenceservices":
            return self.cluster._key(obj)
        if kind == "pods":
            name = obj.metadata.labels.get(ISVC_LABEL)
            if name:
                return f"{obj.metadata.namespace}/{name}"
        return None

    def resync_keys(self):
        return [self.cluster._key(i) for i in self.cluster.list("inferenceservices")]

    # ------------------------------------------------------------- reconcile

    def reconcile(self, key: str) -> float | None:
        isvc: InferenceService | None = self.cluster.get(
            "inferenceservices", key, copy_obj=True
        )
        if isvc is None:
            # cascade: a deleted service must not leave server processes
            # behind (e.g. self-heal recreated a pod mid-deletion)
            ns, _, name = key.partition("/")
            for p in self.cluster.list(
                "pods",
                lambda p: p.metadata.labels.get(ISVC_LABEL) == name
                and p.metadata.namespace == ns,
            ):
                self.cluster.delete("pods", p.key)
            self._seen.discard(key)
            return None
        if key not in self._seen:
            self._seen.add(key)
            self.metrics["isvc_created_total"] += 1
        pods = self._owned_pods(isvc)

        # self-heal: serving replicas must always run; any exited replica
        # (crash OR clean exit) is replaced
        for p in pods:
            if p.status.phase in (PodPhase.FAILED, PodPhase.SUCCEEDED):
                self.cluster.delete("pods", p.key)
                self.metrics["predictor_pods_restarted_total"] += 1
                self.cluster.record_event(
                    "inferenceservices", key, "PredictorRestarted",
                    f"replica {p.metadata.name} exited "
                    f"(code {p.status.exit_code}); recreating",
                    type="Warning",
                )
        pods = [p for p in pods if p.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)]

        # create missing replicas
        have = {int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)) for p in pods}
        created = 0
        for i in range(isvc.spec.predictor.replicas):
            if i not in have:
                self._create_replica(isvc, i)
                created += 1
        # drop excess replicas after a scale-down (highest index first)
        for p in sorted(
            pods,
            key=lambda p: int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)),
            reverse=True,
        ):
            if int(p.metadata.labels.get(REPLICA_INDEX_LABEL, -1)) >= isvc.spec.predictor.replicas:
                self.cluster.delete("pods", p.key)
        pods = self._owned_pods(isvc)

        # probe readiness per running replica (concurrently: each probe can
        # block up to its timeout)
        from kubeflow_tpu.serving.api import ReplicaEndpoint

        ordered = sorted(
            pods, key=lambda p: int(p.metadata.labels.get(REPLICA_INDEX_LABEL, 0))
        )
        urls = [
            f"http://127.0.0.1:{p.metadata.annotations.get(PORT_ANNOTATION, '')}"
            if p.metadata.annotations.get(PORT_ANNOTATION) else ""
            for p in ordered
        ]
        futures = [
            self._probe_pool.submit(probe_ready, url)
            if (p.status.phase == PodPhase.RUNNING and url) else None
            for p, url in zip(ordered, urls)
        ]
        endpoints = [
            ReplicaEndpoint(url=url, ready=(f is not None and f.result()))
            for url, f in zip(urls, futures)
        ]

        st = isvc.status
        before = (st.ready, st.replicas_ready, st.url,
                  tuple((e.url, e.ready) for e in st.endpoints))
        st.endpoints = endpoints
        st.replicas_ready = sum(1 for e in endpoints if e.ready)
        newly_ready = st.replicas_ready > 0 and not st.ready
        st.ready = st.replicas_ready > 0
        ready_eps = [e for e in endpoints if e.ready]
        st.url = ready_eps[0].url if ready_eps else ""
        after = (st.ready, st.replicas_ready, st.url,
                 tuple((e.url, e.ready) for e in st.endpoints))
        if before != after:
            self.cluster.update("inferenceservices", isvc)
            if newly_ready:
                self.metrics["isvc_ready_total"] += 1
                self.cluster.record_event(
                    "inferenceservices", key, "Ready",
                    f"{st.replicas_ready}/{isvc.spec.predictor.replicas} "
                    f"replicas ready at {st.url}",
                )
        # keep probing until the full replica set is ready
        if created or st.replicas_ready < isvc.spec.predictor.replicas:
            return 0.3
        return None

    # ------------------------------------------------------------- sub-steps

    def _owned_pods(self, isvc: InferenceService) -> list[Pod]:
        return self.cluster.list(
            "pods",
            lambda p: p.metadata.labels.get(ISVC_LABEL) == isvc.metadata.name
            and p.metadata.namespace == isvc.metadata.namespace,
        )

    def _create_replica(self, isvc: InferenceService, index: int) -> None:
        p = isvc.spec.predictor
        port = free_port()
        cmd = [
            sys.executable, "-m", "kubeflow_tpu.serving.server",
            "--model-name", isvc.metadata.name,
            "--runtime", p.runtime.value,
            "--port", str(port),
            # per-replica dir: concurrent replicas pulling the same model
            # must not clobber each other's files mid-load
            "--model-dir",
            f"{self.model_cache_dir}/{isvc.metadata.namespace}/r{index}",
        ]
        if p.storage_uri:
            cmd += ["--storage-uri", p.storage_uri]
        if p.model_class:
            cmd += ["--model-class", p.model_class]
        if p.device:
            cmd += ["--device", p.device]
        if isvc.spec.transformer is not None:
            cmd += ["--transformer-class", isvc.spec.transformer.model_class]
        env = dict(p.env)
        env["PYTHONPATH"] = _PKG_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else (os.pathsep + os.environ["PYTHONPATH"] if os.environ.get("PYTHONPATH") else "")
        )
        pod = Pod(
            metadata=ObjectMeta(
                name=f"{isvc.metadata.name}-predictor-{index}",
                namespace=isvc.metadata.namespace,
                labels={
                    ISVC_LABEL: isvc.metadata.name,
                    REPLICA_INDEX_LABEL: str(index),
                },
                annotations={PORT_ANNOTATION: str(port)},
            ),
            command=cmd,
            env=env,
            scheduler_name="default",  # serving pods bypass gang scheduling
        )
        from kubeflow_tpu.controller.poddefault import apply_pod_defaults

        apply_pod_defaults(self.cluster, pod)  # admission mutation
        try:
            self.cluster.create("pods", pod)
        except KeyError:
            return  # replaced concurrently
        self.metrics["predictor_pods_created_total"] += 1
