"""Fleet router — queue-depth-aware routing + SLO admission over N engines.

The tier between the activator and the ContinuousBatcher replicas
(ROADMAP item 2). One engine per InferenceService caps throughput at one
chip's decode bandwidth; the fleet runs N replica engines behind ONE
submit() surface with three production behaviors the solo engine lacks:

  - **least-loaded routing**: every submit lands on the replica with the
    smallest pending-token load (queued prompts + in-flight remaining
    budgets), not round-robin — a replica stuck behind a 4k-token prompt
    stops receiving traffic until it drains;
  - **SLO admission control**: estimated TTFT (pending tokens ahead /
    the fleet's observed service rate) beyond `ttft_slo_s` sheds the
    request with FleetOverloaded carrying a Retry-After hint — the same
    503 + Retry-After contract the activator already speaks, so clients
    (serving/client.py `_post`) re-dial on the server's schedule instead
    of piling onto a saturated fleet;
  - **zero-drop replica kill**: when a replica dies mid-flight, every
    request it was carrying — queued or decoding — is requeued onto a
    surviving replica via the engines' on_done callbacks; nothing is
    dropped, and `requeued_total` counts the disruption. A request whose
    paged-KV chain survives the kill RESUMES from it on the survivor
    (tokens kept, zero re-prefill, zero re-decode —
    `requeues_resumed_total` / `requeue_resumed_tokens_total` count the
    rescue); only a chainless request re-decodes from scratch, and
    greedy rows then re-decode to identical tokens either way.

**Disaggregated prefill/decode** (replica `role`): tag replicas
"prefill" / "decode" (default "mixed") and the router splits the
request lifetime across tiers — new requests route least-loaded onto
the prefill tier, which runs chunked prefill (budget-1, `keep_chain`)
and publishes the finished block chain through the SHARED paged pool;
the router hands the chain to a decode replica whose resume admission
seeds its row cache from the pool and decodes from the first generated
position. Long prompts never occupy a decode slot, and pure-prefill
replicas lift the one-chunk-per-tick stall bound
(`max_chunks_per_tick`) because they have no decode rows to starve.

The demand signal (`demand_replicas()`) is the autoscaler's input:
pending tokens over (service rate x TTFT SLO), clamped to at least the
alive replica count when queues are hot — the `kftpu_fleet_*` queue and
latency families in /metrics carry the same numbers for dashboards.

Paged-KV prefix reuse composes: hand each replica engine the SAME
PagedKVPool and a system prompt prefills once per fleet, not once per
replica admission (docs/serving.md).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.serving.fleet.wire import PodCallError, PodDead
from kubeflow_tpu.tracing.core import armed_tracer, current_context

#: EWMA weight of each completed request's observed decode rate
_RATE_ALPHA = 0.2

#: bound on the TTFT sample window backing the p50/p99 gauges
_TTFT_WINDOW = 512


class FleetOverloaded(RuntimeError):
    """Admission shed: the fleet cannot meet the TTFT SLO for this
    request. `retry_after_s` is the server-side hint the HTTP surfaces
    forward as a 503 Retry-After header. `trace_ctx`/`request_id` are
    stamped by submit() when tracing is armed, so the 503 body can carry
    the shed decision's span context back to the client
    (serving/server.py — a shed request is attributable, not just
    gone)."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.trace_ctx = None
        self.request_id = ""


@dataclass
class Replica:
    """One engine slot in the fleet: the ContinuousBatcher plus the
    router's liveness view of it. `role` places it in the disaggregated
    split: "mixed" (default) serves whole requests, "prefill" serves the
    chunked-prefill leg only (publishing chains through the shared
    pool), "decode" adopts published chains and decodes them."""

    name: str
    engine: object
    alive: bool = True
    role: str = "mixed"
    #: scale-down drain (serving/fleet/scaler.py): a draining replica
    #: stops ADMITTING (excluded from _pick) but keeps ticking its
    #: in-flight rows until empty — a drain is a polite kill_replica,
    #: taken only when the grace window expires with work still seated
    draining: bool = False

    def pending_tokens(self) -> int:
        """The routing load signal: queued prompt+budget tokens plus the
        remaining budgets of in-flight rows. Best-effort reads of the
        ticker-private row table (same contract as the /metrics gauges —
        a mid-tick read is off by at most one row)."""
        eng = self.engine
        with eng._lock:
            queued = sum(ids.size + req.max_new_tokens
                         for ids, req in eng._queue)
        rows = sum(max(req.max_new_tokens - len(req.tokens), 1)
                   for req in eng._rows if req is not None)
        return queued + rows

    def depth(self) -> int:
        eng = self.engine
        with eng._lock:
            queued = len(eng._queue)
        return queued + sum(1 for r in eng._rows if r is not None)


@dataclass
class FleetRequest:
    """Router-level handle: survives replica kills (the engine handle it
    wraps is replaced on requeue). result() blocks for the tokens of the
    final successful attempt; TTFT is measured from fleet submission to
    the first token the CLIENT would have seen (requeues reset it —
    the wait is real)."""

    prompt: np.ndarray
    kwargs: dict
    t_submit: float
    replica: str = ""
    attempts: int = 0
    tokens: list = field(default_factory=list)
    t_first: float | None = None
    t_done: float | None = None
    error: str | None = None
    done: threading.Event = field(default_factory=threading.Event)
    on_token: object = None
    #: client-stream high-water mark: positions already forwarded to
    #: on_token — a re-dispatch re-decoding streamed positions (scratch
    #: requeue, frozen-chain fallback) must not re-deliver them
    delivered: int = 0
    # disaggregated / resume state: `stage` is the lifetime leg the next
    # dispatch serves ("" = whole request on a mixed replica, "prefill"
    # = the budget-1 chain-publishing leg, "decode" = adopt-and-decode);
    # `chain` is a surviving SequenceChain waiting to be handed to the
    # next engine (ownership passes on dispatch); `budget`/`eos` are the
    # request's resolved decode budget and stop set (the router needs
    # them to split the lifetime without re-deriving engine defaults).
    stage: str = ""
    chain: object = None
    budget: int = 0
    eos: tuple | None = None
    # request-tracing state: the router owns the `request` root span for
    # fleet requests — trace_ctx is its pre-allocated identity (engine
    # phase spans parent to it across requeues), recorded retroactively
    # when the request completes/sheds/fails (docs/slo.md)
    trace_ctx: object = None
    parent_ctx: object = None
    request_id: str = ""
    _tracer: object = None
    t_submit_wall: float = 0.0

    @property
    def ttft_s(self) -> float | None:
        return None if self.t_first is None else self.t_first - self.t_submit

    @property
    def tokens_per_s(self) -> float | None:
        if self.t_first is None or self.t_done is None:
            return None
        dt = self.t_done - self.t_first
        return len(self.tokens) / dt if dt > 0 else float("inf")

    def result(self, timeout: float | None = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("fleet request did not finish in time")
        if self.error is not None:
            raise RuntimeError(f"fleet request failed: {self.error}")
        return np.asarray(self.tokens, np.int32)


class FleetRouter:
    """N replica engines behind one submit() (module docstring)."""

    def __init__(self, replicas, ttft_slo_s: float = 0.0,
                 retry_after_s: float = 1.0,
                 service_rate_tokens_per_s: float = 0.0,
                 max_requeues: int = 3, tracer=None,
                 demand_tokens_per_replica: float = 0.0):
        """replicas: list of engines (named replica-<i>), (name, engine)
        pairs, or (name, engine, role) triples — role "prefill"/"decode"
        arms the disaggregated split (docstring), which requires every
        engine to share ONE paged_kv pool (the chain-handoff medium) and
        at least one replica on each side of the split. ttft_slo_s: 0
        disables admission shedding. service_rate_tokens_per_s: initial
        service-rate estimate; 0 defers admission control until the
        first completion calibrates it. tracer (tracing.Tracer):
        per-request root spans + the kill→requeue causal chain;
        propagated to replica engines that have none of their own, so
        one tracer covers the whole fleet (docs/slo.md)."""
        self.tracer = tracer
        #: monitoring TSDB propagated to replica engines (set by
        #: Platform._wire_fleet); carried here so add_replica — the
        #: autoscaler's scale-out path, active exactly when the burn
        #: monitor is — wires NEW replicas into the decode-tick/TTFT
        #: series too, not just the ones present at registration
        self.tsdb = None
        self.replicas: list[Replica] = []
        for i, r in enumerate(replicas):
            role = "mixed"
            if isinstance(r, tuple):
                name, eng = r[0], r[1]
                if len(r) > 2:
                    role = r[2]
            else:
                name, eng = f"replica-{i}", r
            if role not in ("mixed", "prefill", "decode"):
                raise ValueError(f"unknown replica role {role!r}")
            self._wire_engine(eng)
            self.replicas.append(Replica(name=name, engine=eng, role=role))
        if not self.replicas:
            raise ValueError("a fleet needs at least one replica")
        if self.disaggregated:
            pools = {id(r.engine.paged_kv): r.engine.paged_kv
                     for r in self.replicas}
            if any(p is None for p in pools.values()) or len(pools) != 1:
                raise ValueError(
                    "a disaggregated fleet needs every replica on ONE "
                    "shared paged_kv pool — it is the chain-handoff "
                    "medium")
            if not any(r.role in ("decode", "mixed") for r in self.replicas):
                raise ValueError(
                    "a disaggregated fleet needs at least one decode-"
                    "capable (decode/mixed) replica")
        #: replica name -> the fleet.replica_kill event's SpanContext —
        #: what a requeue parent-links to (the chaos.pod_kill →
        #: gang_restart chain, serving edition)
        self._kill_ctx: dict[str, object] = {}
        #: wake-on-arrival signal (serving/fleet/scaler.py): arrivals
        #: that found NO admittable replica (scaled to zero, or every
        #: survivor draining) are counted here so the demand signal —
        #: whose queue-math EWMA has no live engine updating it in that
        #: state — is pinned to the arrivals themselves, never to a
        #: stale service rate
        self._wake_pending = 0
        self._wake_ts = 0.0
        #: the autoscaler, when one is driving this fleet
        #: (FleetScaler.__init__ sets it; observability and the ISVC
        #: controller wiring read it)
        self.scaler = None
        self.ttft_slo_s = float(ttft_slo_s)
        self.retry_after_s = float(retry_after_s)
        self.max_requeues = int(max_requeues)
        #: explicit per-replica capacity target for the demand signal
        #: (tokens of backlog one replica should own — the working-set
        #: form: replicas x rows x (prompt + budget) is the natural
        #: value). When set it replaces the EWMA-rate x SLO estimate in
        #: demand_replicas(): a scaling POLICY wants to add capacity
        #: BEFORE latency degrades, and the rate estimate only moves
        #: after it has (the tick-driven soak also pins this because
        #: its serialized engine loop distorts wall-clock rates).
        self.demand_tokens_per_replica = float(demand_tokens_per_replica)
        self._rate = float(service_rate_tokens_per_s)
        self._mu = make_lock("fleet.FleetRouter._mu")
        self._ttfts = collections.deque(maxlen=_TTFT_WINDOW)
        self.metrics = {
            "requests_admitted_total": 0,
            "requests_shed_total": 0,
            "requests_requeued_total": 0,
            "requeues_resumed_total": 0,
            "requeue_resumed_tokens_total": 0,
            "prefill_handoffs_total": 0,
            "requests_completed_total": 0,
            "requests_failed_total": 0,
            "replica_kills_total": 0,
        }

    @property
    def disaggregated(self) -> bool:
        return any(r.role == "prefill" for r in self.replicas)

    def _wire_engine(self, engine) -> None:
        """The ONE engine-attach path for the fleet's tracer + TSDB
        (constructor, add_replica, and Platform._wire_fleet all funnel
        here): an engine that brought its own keeps it; any future
        replica-attach path inherits both or neither, never a drifted
        half."""
        if self.tracer is not None \
                and getattr(engine, "tracer", None) is None:
            engine.tracer = self.tracer
        if self.tsdb is not None \
                and getattr(engine, "tsdb", None) is None:
            engine.tsdb = self.tsdb
        # mark the engine router-managed: _fail_all may transfer a dying
        # row's chain to the handle ONLY when this router's requeue is
        # listening to release-or-resume it — a direct engine consumer
        # with an on_done callback would otherwise leak pinned blocks
        engine._fleet_managed = True

    def wire_monitoring(self, tracer=None, tsdb=None) -> None:
        """Late-attach monitoring to the whole fleet (Platform wiring:
        register_fleet / start_tracing / start_slo in any order): set
        the fleet-level tracer/TSDB unless already present, then wire
        every current replica. Future add_replica calls inherit
        automatically."""
        if tracer is not None and self.tracer is None:
            self.tracer = tracer
        if tsdb is not None and self.tsdb is None:
            self.tsdb = tsdb
        for rep in self.replicas:
            self._wire_engine(rep.engine)

    # ----------------------------------------------------------- routing

    def _alive(self) -> list[Replica]:
        return [r for r in self.replicas if r.alive]

    def _admittable(self) -> list[Replica]:
        """Replicas a NEW dispatch may land on: alive and not draining
        (a draining replica still ticks its in-flight rows — the drain
        contract — but admits nothing)."""
        return [r for r in self.replicas if r.alive and not r.draining]

    def load_view(self) -> dict[str, int]:
        """Per-replica pending-token load — the activator's queue-depth-
        aware endpoint pick reads this (serving/activator.py)."""
        return {r.name: r.pending_tokens() for r in self.replicas if r.alive}

    def queue_depth(self) -> int:
        return sum(r.depth() for r in self._alive())

    def pending_tokens(self) -> int:
        return sum(r.pending_tokens() for r in self._alive())

    def estimated_ttft_s(self, prompt_len: int) -> float | None:
        """Admission estimate: tokens ahead of this prompt's first token
        over the fleet's observed service rate. None until a completion
        has calibrated the rate (admission stays open — shedding on a
        guess would turn cold starts into outages)."""
        if self._rate <= 0.0:
            return None
        alive = self._admittable()
        if not alive:
            return float("inf")
        ahead = min(r.pending_tokens() for r in alive) + prompt_len
        return ahead / self._rate

    def admit_or_raise(self, prompt_tokens: int) -> None:
        """The admission gate alone: raises FleetOverloaded when the
        estimated TTFT for `prompt_tokens` more prompt work exceeds the
        SLO. Callers submitting a BATCH gate once with the batch total
        (then submit ungated) so a shed can never orphan half-admitted
        rows on the fleet."""
        est = self.estimated_ttft_s(prompt_tokens)
        if self.ttft_slo_s > 0.0 and est is not None \
                and est > self.ttft_slo_s:
            with self._mu:
                self.metrics["requests_shed_total"] += 1
            raise FleetOverloaded(
                f"estimated TTFT {est:.3f}s exceeds SLO "
                f"{self.ttft_slo_s:.3f}s", retry_after_s=max(
                    self.retry_after_s,
                    min(est - self.ttft_slo_s, 30.0)))

    def submit(self, prompt_ids, gate: bool = True,
               **kwargs) -> FleetRequest:
        """Admission-gate then route to the least-loaded live replica.
        Raises FleetOverloaded (with retry_after_s) on shed — including
        when no replica is alive, counted as a shed, never as an
        admission. With tracing armed every request gets a `request`
        root span (recorded retroactively at completion) whose children
        are the admission decision, per-attempt dispatches, and the
        engine's queue-wait/prefill-chunk/decode spans."""
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if not self.replicas:
            # scaled to zero with the replica LIST empty (the scaler
            # reaps drained shells): there is no engine to resolve
            # defaults from — shed with the wake signal stamped, the
            # same contract _pick applies when entries exist but none
            # admit. Found by the prod_day soak's first scale-to-zero.
            with self._mu:
                self._wake_pending += 1
                self._wake_ts = time.time()
                self.metrics["requests_shed_total"] += 1
            raise FleetOverloaded("no live replicas",
                                  retry_after_s=self.retry_after_s)
        on_token = kwargs.pop("on_token", None)
        rid = kwargs.pop("request_id", "")
        freq = FleetRequest(prompt=ids, kwargs=dict(kwargs),
                            t_submit=time.perf_counter(),
                            on_token=on_token)
        freq.t_submit_wall = time.time()
        # resolve the lifetime split's inputs once: the decode budget and
        # stop set (engine defaults otherwise live behind the dispatch)
        eng0 = self.replicas[0].engine
        freq.budget = int(kwargs.get("max_new_tokens")
                          or eng0.default_max_new_tokens)
        if kwargs.get("eos_token_id") is not None:
            from kubeflow_tpu.serving.continuous import _eos_tuple

            freq.eos = _eos_tuple(kwargs["eos_token_id"])
        else:
            freq.eos = eng0.eos_token_id
        freq.stage = "prefill" if self.disaggregated else ""
        tr = armed_tracer(self.tracer)
        if tr is not None:
            if not rid:
                from kubeflow_tpu.serving.requestid import get_request_id

                rid = get_request_id()
            freq._tracer = tr
            freq.parent_ctx = current_context()
            freq.trace_ctx = tr.allocate_context(parent=freq.parent_ctx)
        freq.request_id = rid
        try:
            if gate:
                self.admit_or_raise(ids.size)
        except FleetOverloaded as exc:
            self._trace_shed(freq, exc)
            raise
        if tr is not None:
            tr.event("request.admission", parent=freq.trace_ctx,
                     decision="admit", prompt_tokens=int(ids.size),
                     request_id=freq.request_id)
        try:
            self._dispatch(freq)
        except FleetOverloaded as exc:
            with self._mu:
                self.metrics["requests_shed_total"] += 1
            self._trace_shed(freq, exc)
            raise
        # counted only once the request is really on a replica, so
        # admitted == completed + failed + in-flight always holds
        with self._mu:
            self.metrics["requests_admitted_total"] += 1
        return freq

    def _trace_shed(self, freq: FleetRequest, exc: FleetOverloaded) -> None:
        """Record the shed decision as the request's (terminal) trace and
        hand its context to the exception so the 503 body can carry it."""
        exc.trace_ctx = freq.trace_ctx
        exc.request_id = freq.request_id
        if freq._tracer is None:
            return
        freq._tracer.event(
            "request.admission", parent=freq.trace_ctx, decision="shed",
            retry_after_s=round(exc.retry_after_s, 3),
            request_id=freq.request_id)
        freq._tracer.record_span(
            "request", freq.t_submit_wall,
            time.perf_counter() - freq.t_submit, context=freq.trace_ctx,
            parent=freq.parent_ctx, request_id=freq.request_id,
            outcome="shed")

    def record_shed(self, exc: FleetOverloaded, prompt_tokens: int,
                    request_id: str = "") -> FleetOverloaded:
        """Trace a shed decided OUTSIDE submit() — the batch-gate path
        (JaxModel gates once with the whole batch via admit_or_raise,
        then submits ungated): records the shed `request` root +
        admission event and stamps the exception's trace_ctx/request_id
        so the 503 body carries them, exactly like a submit()-path shed.
        Returns the (mutated) exception for `raise ... from` chains."""
        tr = armed_tracer(self.tracer)
        if not request_id:
            from kubeflow_tpu.serving.requestid import get_request_id

            request_id = get_request_id()
        exc.request_id = request_id
        if tr is None:
            return exc
        parent = current_context()
        ctx = tr.allocate_context(parent=parent)
        tr.event("request.admission", parent=ctx, decision="shed",
                 prompt_tokens=int(prompt_tokens),
                 retry_after_s=round(exc.retry_after_s, 3),
                 request_id=request_id)
        tr.record_span("request", time.time(), 0.0, context=ctx,
                       parent=parent, request_id=request_id,
                       outcome="shed")
        exc.trace_ctx = ctx
        return exc

    def _pick(self, stage: str = "") -> Replica:
        alive = self._admittable()
        if not alive:
            # wake-on-arrival: the arrival is the scale-from-zero demand
            # signal (the activator's DEMAND_ANNOTATION, in-process) —
            # recorded BEFORE the shed so demand_replicas() sees it even
            # though this request bounces with Retry-After
            self._wake_pending += 1
            self._wake_ts = time.time()
            raise FleetOverloaded("no live replicas",
                                  retry_after_s=self.retry_after_s)
        if self.disaggregated and stage:
            # tier-aware pick: the prefill leg lands on prefill-capable
            # replicas, the decode leg on decode-capable ones. A wiped
            # tier degrades to any live replica (every engine CAN do
            # both — the role is a routing policy, not a capability)
            want = (("prefill", "mixed") if stage == "prefill"
                    else ("decode", "mixed"))
            tier = [r for r in alive if r.role in want]
            alive = tier or alive
        return min(alive, key=lambda r: r.pending_tokens())

    def _dispatch(self, freq: FleetRequest, handoff: bool = False) -> None:
        # the fleet handle rides INSIDE the engine callbacks (partial
        # binding) — a registry keyed on the engine handle would race the
        # replica's ticker, which can emit tokens between submit() and
        # any later registration. The pick AND the enqueue happen under
        # _mu, ordered against kill_replica's alive=False flip (also
        # under _mu): either this dispatch lands before the kill — and
        # the kill's _fail_all requeues it — or the pick already excludes
        # the corpse. Without the ordering, an enqueue racing the kill
        # strands the request on a stopped ticker's queue forever.
        from functools import partial

        kwargs = dict(freq.kwargs)
        # the budget/stop set resolved at submit() govern the WHOLE
        # lifetime regardless of which replica serves a leg: engines in
        # one fleet may carry different defaults, and the split/resume
        # arithmetic (and the prefill leg's `finished` check) must not
        # shift with the replica the dispatch happens to land on
        kwargs["max_new_tokens"] = freq.budget
        if freq.eos is not None and "eos_token_id" not in kwargs:
            kwargs["eos_token_id"] = freq.eos
        chain, resume_tokens = None, None
        if freq.stage == "prefill":
            # the chain-publishing leg: emit the first token only, keep
            # the finished chain on the handle for the decode tier
            kwargs["max_new_tokens"] = 1
            kwargs["keep_chain"] = True
        elif freq.chain is not None:
            # adopt-and-decode (the disagg handoff / kill-requeue
            # resume): ownership of the chain passes to the engine
            chain, resume_tokens = freq.chain, list(freq.tokens)
            kwargs["resume_from"] = (chain, resume_tokens)
        # pod-backed replicas can die INSIDE submit (the wire fails
        # before the request ever seats): the admission-window gap the
        # in-process ordering comment above cannot cover. The dispatch
        # loop absorbs it under _mu — flip the corpse, re-pick a
        # survivor — and propagates the death (requeue callbacks for
        # whatever the corpse carried) only after _mu is released,
        # because those callbacks re-enter this very lock.
        corpses = []
        try:
            with self._mu:
                if not handoff:
                    # a handoff is one lifetime split across tiers, not
                    # a retry — attempts stays the requeue odometer
                    freq.attempts += 1
                while True:
                    rep = self._pick(freq.stage)
                    freq.replica = rep.name
                    if freq._tracer is not None:
                        freq._tracer.event(
                            "fleet.dispatch", parent=freq.trace_ctx,
                            replica=rep.name, attempt=freq.attempts,
                            stage=freq.stage or "full",
                            request_id=freq.request_id)
                    try:
                        rep.engine.submit(
                            freq.prompt,
                            on_token=partial(self._on_token, freq),
                            on_done=partial(self._on_done, freq),
                            trace_ctx=freq.trace_ctx,
                            request_id=freq.request_id, **kwargs)
                    except PodDead:
                        rep.alive = False
                        self.metrics["replica_kills_total"] += 1
                        corpses.append(rep.engine)
                        continue
                    break
                if chain is not None:
                    freq.chain = None  # the engine owns it now
        except PodCallError as exc:
            if exc.code != 409 or chain is None:
                raise
            # resume refused by the worker (chain frozen on re-insert —
            # the receiving pool could not cover every position): the
            # client already released the home chain; fall back to a
            # whole-lifetime scratch dispatch, same as the frozen-chain
            # path in _on_done. `delivered` keeps the stream single-copy
            # across the re-decode.
            freq.chain = None
            freq.tokens = []
            freq.t_first = None
            freq.stage = "prefill" if self.disaggregated else ""
            self._dispatch(freq, handoff=True)
        finally:
            for eng in corpses:
                eng._propagate_death()

    # --------------------------------------------- engine-thread callbacks

    def _on_token(self, freq: FleetRequest, handle, tok: int) -> None:
        if freq.done.is_set():
            return
        if freq.t_first is None:
            freq.t_first = time.perf_counter()
        freq.tokens.append(tok)
        # `delivered` is the client's high-water mark: a re-dispatch that
        # re-decodes already-streamed positions (scratch requeue, the
        # frozen-chain fallback) re-emits them into freq.tokens, but the
        # client's on_token must see each position ONCE (greedy re-decode
        # reproduces them identically, so skipping is exact)
        if freq.on_token is not None and len(freq.tokens) > freq.delivered:
            freq.on_token(freq, tok)
        freq.delivered = max(freq.delivered, len(freq.tokens))

    def _on_done(self, freq: FleetRequest, handle) -> None:
        """Runs on the finishing replica's engine thread. Success
        completes the fleet handle (or, on the disaggregated prefill
        leg, hands the published chain to the decode tier); a
        replica-death failure requeues onto a survivor — the zero-drop
        contract — RESUMING from the surviving paged-KV chain when one
        exists instead of re-decoding from scratch."""
        if freq.done.is_set():
            return
        if handle.error is None:
            if freq.stage == "prefill":
                freq.tokens = [int(t) for t in handle.tokens]
                chain = getattr(handle, "chain", None)
                if chain is not None and chain.frozen:
                    # insert() stopped early at admission (covered-by-
                    # sibling / partial-parent boundary), so the chain
                    # cannot cover the row's positions: nothing to hand
                    # off — release it and take the chainless fallback
                    # (a frozen chain must never reach resume_from:
                    # submit refuses it, and on this engine-thread
                    # callback that refusal would strand the client)
                    chain.release()
                    handle.chain = None
                    chain = None
                finished = (len(freq.tokens) >= freq.budget
                            or (freq.eos is not None
                                and freq.tokens[-1] in freq.eos))
                if not finished:
                    freq.stage = "decode"
                    if chain is not None:
                        # the handoff: the prefill replica published the
                        # chain through the shared pool; a decode
                        # replica adopts it and decodes from the first
                        # generated position — the prompt never touches
                        # a decode slot
                        freq.chain = chain
                        with self._mu:
                            self.metrics["prefill_handoffs_total"] += 1
                        if freq._tracer is not None:
                            freq._tracer.event(
                                "fleet.handoff", parent=freq.trace_ctx,
                                request_id=freq.request_id,
                                from_replica=freq.replica,
                                chain_blocks=len(chain.refs),
                                chain_tokens=int(chain.length))
                    else:
                        # frozen/unpublishable chain: fall back to a
                        # whole-lifetime dispatch on the decode tier
                        # (every engine CAN prefill; the split is
                        # policy, not capability). The re-decode
                        # re-emits the first token; `delivered` keeps
                        # the client stream single-copy.
                        freq.tokens = []
                    try:
                        self._dispatch(freq, handoff=True)
                    except FleetOverloaded as exc:
                        self._fail(freq, str(exc))
                    return
                if chain is not None:
                    chain.release()  # finished at the first token
            else:
                # prefill-finished fall-through already normalized above
                freq.tokens = [int(t) for t in handle.tokens]
            freq.t_done = time.perf_counter()
            with self._mu:
                self.metrics["requests_completed_total"] += 1
                if freq.ttft_s is not None:
                    self._ttfts.append(freq.ttft_s)
                self._observe_rate(freq)
            self._record_root(freq, "completed")
            freq.done.set()
            return
        chain = getattr(handle, "chain", None)
        if freq.attempts > self.max_requeues:
            if chain is not None:
                chain.release()
                handle.chain = None
            self._fail(freq, f"gave up after {freq.attempts} attempts: "
                             f"{handle.error}")
            return
        # replica died (or poisoned round): continue on a survivor. A
        # surviving chain (transferred by the dead engine's _fail_all)
        # RESUMES — emitted tokens kept, TTFT kept, zero re-prefill and
        # zero re-decode; without one, partial tokens are discarded and
        # greedy decode reproduces them exactly from scratch.
        # token record: freq.tokens is the router's own (what the client
        # already streamed) — for a request killed while still QUEUED on
        # the dead replica's resume path, handle.tokens is empty but the
        # prefill leg's first token lives in freq.tokens and the chain
        # still rescues; for a seated row the two agree (every emission
        # flowed through _on_token). The rescue also requires every live
        # replica to share the chain's pool: a mixed fleet with
        # per-replica pools (legal, pre-dating the disagg split) must
        # take the scratch path — resume_from into a different pool is
        # an engine-side refusal this engine-thread callback cannot
        # surface to the client
        resumed = (chain is not None and not chain.frozen
                   and chain.length >= freq.prompt.size
                   and len(freq.tokens) > 0
                   and all(r.engine.paged_kv is chain.pool
                           for r in self._alive()))
        if resumed:
            keep = int(chain.length) - int(freq.prompt.size) + 1
            freq.tokens = [int(t) for t in freq.tokens][:keep]
            freq.chain = chain
            freq.stage = "decode" if self.disaggregated else ""
        else:
            if chain is not None:
                chain.release()
            keep = 0
            freq.tokens = []
            freq.t_first = None
            freq.stage = "prefill" if self.disaggregated else ""
        handle.chain = None
        with self._mu:
            self.metrics["requests_requeued_total"] += 1
            if resumed:
                self.metrics["requeues_resumed_total"] += 1
                self.metrics["requeue_resumed_tokens_total"] += keep
        if freq._tracer is not None:
            # parent-linked to the replica-kill event exactly like the
            # chaos.pod_kill → job.gang_restart chain: the kill is the
            # ROOT of the disruption, each requeue a consequence of it
            # (falls back to the request's own trace for a non-kill
            # poisoned round). resumed_from_block attributes the rescue:
            # how many surviving pool blocks the requeue resumed from
            # (0 = the PR-9 re-decode-from-scratch fallback).
            freq._tracer.event(
                "fleet.requeue",
                parent=self._kill_ctx.get(freq.replica) or freq.trace_ctx,
                request_id=freq.request_id, from_replica=freq.replica,
                attempt=freq.attempts,
                resumed_from_block=len(chain.refs) if resumed else 0,
                resumed_tokens=keep)
        try:
            self._dispatch(freq)
        except FleetOverloaded as exc:
            self._fail(freq, str(exc))

    def _fail(self, freq: FleetRequest, error: str) -> None:
        """Terminal failure: release any chain the request still owns,
        count, record, unblock."""
        if freq.chain is not None:
            freq.chain.release()
            freq.chain = None
        freq.error = error
        with self._mu:
            self.metrics["requests_failed_total"] += 1
        self._record_root(freq, "failed")
        freq.done.set()

    def _record_root(self, freq: FleetRequest, outcome: str) -> None:
        """Retroactively record the request's root span at its terminal
        transition (the one place done.set() is reached from)."""
        if freq._tracer is None:
            return
        end = freq.t_done if freq.t_done is not None \
            else time.perf_counter()
        attrs = {"request_id": freq.request_id, "outcome": outcome,
                 "attempts": freq.attempts, "replica": freq.replica,
                 "tokens": len(freq.tokens)}
        if freq.error is not None:
            attrs["error"] = freq.error
        freq._tracer.record_span(
            "request", freq.t_submit_wall, end - freq.t_submit,
            context=freq.trace_ctx, parent=freq.parent_ctx, **attrs)

    def _observe_rate(self, freq: FleetRequest) -> None:
        """EWMA of completed requests' SERVICE token rate — PROMPT +
        output tokens over the served window (submit-or-first-token to
        done), the same unit pending_tokens() counts (queued prompts +
        budgets). Mixing prompt/output units here would inflate
        estimated TTFT by their ratio and shed long-prompt traffic the
        fleet could comfortably serve.

        The window deliberately EXCLUDES queue wait (it starts at the
        first token when one exists): estimated TTFT divides the
        backlog by this rate, so folding queueing into the denominator
        is a positive feedback loop — a transient backlog depresses the
        "rate", which sheds admissions, which stops completions, which
        pins the rate low FOREVER (nothing completes while everything
        sheds). The prod_day soak found exactly that shed-lock: one
        congested peak and the fleet refused traffic it was idle for.
        Caller holds _mu."""
        done = freq.t_done or 0.0
        served = done - (freq.t_first
                         if freq.t_first is not None else freq.t_submit)
        if served <= 0.0:
            return
        rate = (freq.prompt.size + len(freq.tokens)) / served
        self._rate = (rate if self._rate <= 0.0
                      else (1 - _RATE_ALPHA) * self._rate
                      + _RATE_ALPHA * rate)

    @property
    def service_rate_tokens_per_s(self) -> float:
        return self._rate

    # ------------------------------------------------------------ chaos

    def kill_replica(self, name_or_idx, parent=None) -> Replica:
        """Chaos entry (the drills' mid-run kill): stop the replica's
        ticker and fail everything it carries — the on_done callbacks
        requeue every request onto the survivors. `parent` links the
        kill event under a decision span (the scaler's drain-timeout
        polite kill parents it to the fleet.scale_down that ordered the
        drain); None keeps the kill a root — the chaos shape."""
        rep = self._resolve(name_or_idx)
        tr = armed_tracer(self.tracer)
        if tr is not None:
            # the root of the disruption chain (the serving analogue of
            # chaos.pod_kill): every request the corpse was carrying
            # parent-links its fleet.requeue here — stamped BEFORE
            # _fail_all so the requeue callbacks can see it
            ev = tr.event("fleet.replica_kill", parent=parent,
                          replica=rep.name)
            if ev.context is not None:
                self._kill_ctx[rep.name] = ev.context
        with self._mu:
            # ordered against _dispatch (also under _mu): any dispatch
            # that won the race has ALREADY enqueued, so the _fail_all
            # below requeues it; later picks exclude the corpse
            rep.alive = False
            self.metrics["replica_kills_total"] += 1
        rep.engine.stop()
        rep.engine._fail_all("replica killed")
        return rep

    def add_replica(self, engine, name: str = "",
                    role: str = "mixed") -> Replica:
        """Scale-out entry (the autoscaler's add path). The new engine
        inherits the fleet's tracer AND monitoring TSDB (unless it
        brought its own), so scale-out replicas are visible to the SLO
        series from their first tick. On a disaggregated fleet the
        constructor's invariant holds here too: the new engine must
        share the ONE paged_kv pool (a decode-capable replica off the
        pool would crash the chain handoff/resume on an engine-thread
        callback, stranding the client)."""
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        pools = {id(r.engine.paged_kv): r.engine.paged_kv
                 for r in self.replicas}
        if self.disaggregated or role == "prefill":
            pools[id(engine.paged_kv)] = engine.paged_kv
            if any(p is None for p in pools.values()) or len(pools) != 1:
                raise ValueError(
                    "a disaggregated fleet needs every replica on ONE "
                    "shared paged_kv pool — it is the chain-handoff "
                    "medium")
        elif (len(pools) == 1
              and next(iter(pools.values())) is not None
              and engine.paged_kv is not next(iter(pools.values()))):
            # a fleet whose replicas all share one pool is resume-
            # capable: the kill-requeue guard decides "every live
            # replica shares the chain's pool" and then _pick may land
            # the resume on ANY replica — admitting an off-pool engine
            # here would let that dispatch race into an engine-side
            # refusal on the callback thread
            raise ValueError(
                "this fleet's replicas share one paged_kv pool (the "
                "resume-from-KV rescue dispatches chains to any "
                "replica) — scale-out engines must share it too")
        self._wire_engine(engine)
        rep = Replica(name=name or f"replica-{len(self.replicas)}",
                      engine=engine, role=role)
        self.replicas.append(rep)
        return rep

    def begin_drain(self, name_or_idx) -> Replica:
        """Scale-down entry (the scaler's graceful half): the replica
        stops admitting — _pick excludes it, ordered under _mu against
        in-flight dispatches exactly like kill_replica's alive flip —
        but keeps ticking its seated rows. In-flight requests finish in
        place; remove_replica() reaps the empty shell, and a drain that
        outlives its grace window is finished as a polite kill_replica
        (the PR-13 requeue resumes every survivor from its chain)."""
        rep = self._resolve(name_or_idx)
        with self._mu:
            rep.draining = True
        return rep

    def cancel_drain(self, name_or_idx) -> Replica:
        """Un-drain: the cheapest scale-up (no cold start) when demand
        returns before the drain finished."""
        rep = self._resolve(name_or_idx)
        with self._mu:
            rep.draining = False
        return rep

    def remove_replica(self, name_or_idx) -> Replica:
        """Reap a replica that can no longer carry work: drained empty,
        or dead (killed — _fail_all already requeued its requests). A
        live admitting replica, or a draining one with rows still
        seated, is refused — removal would strand its clients."""
        rep = self._resolve(name_or_idx)
        with self._mu:
            if rep.alive and (not rep.draining or rep.depth() > 0):
                raise ValueError(
                    f"replica {rep.name!r} still carries work (or still "
                    "admits) — drain it empty or kill_replica first")
            self.replicas.remove(rep)
        return rep

    def _resolve(self, name_or_idx) -> Replica:
        return (self.replicas[name_or_idx]
                if isinstance(name_or_idx, int)
                else next(r for r in self.replicas
                          if r.name == name_or_idx))

    # ------------------------------------------------------- autoscaling

    def demand_replicas(self) -> int:
        """Desired replica count from the queue/latency signal: pending
        tokens over what ONE replica can serve inside the TTFT SLO (the
        EWMA service rate is a per-request — i.e. per-replica-queue —
        rate, so it is NOT divided by the alive count: demand must
        depend on the backlog, not on how many replicas currently exist,
        or scale-out would raise its own demand signal). The floor is
        the number of BUSY replicas (scale-in only below actual use);
        the ceiling is the autoscaler's call.

        Scaled-to-zero guard: with no admittable replica the EWMA
        service rate has no live engine updating it, so the queue math
        is pinned instead of trusted — any queued work or any arrival
        recorded since the fleet emptied (the wake signal _pick stamps
        before shedding) demands one replica, and only a truly idle
        fleet demands zero (the scale-to-zero steady state). The signal
        can therefore never return 0 while anything is waiting."""
        alive = self._alive()
        serving = [r for r in alive if not r.draining]
        if not serving:
            backlog = sum(r.pending_tokens() for r in alive)
            return 1 if (self._wake_pending > 0 or backlog > 0) else 0
        busy = sum(1 for r in serving if r.depth() > 0)
        per_replica = (self.demand_tokens_per_replica
                       or self._rate * self.ttft_slo_s)
        if per_replica <= 0.0:
            return max(1, busy)
        import math

        return max(1, busy, math.ceil(self.pending_tokens() / per_replica))

    def wake_pending(self) -> int:
        """Arrivals shed for want of ANY admittable replica since the
        last clear — the scale-from-zero trigger the scaler consumes."""
        with self._mu:
            return self._wake_pending

    def clear_wake(self) -> None:
        """Scaler acknowledgment: capacity is being added for the
        recorded arrivals (FleetScaler's scale-from-zero path)."""
        with self._mu:
            self._wake_pending = 0

    #: the burn-rate multiplier on demand is clamped here: a saturated
    #: (capped) burn must scale the fleet decisively, not to infinity
    BURN_DEMAND_CAP = 4.0

    def demand_replicas_burn(self, monitor,
                             slos: tuple[str, ...] = (
                                 "serving_ttft_p99",
                                 "serving_decode_tick",
                                 "serving_zero_drop")) -> int:
        """Burn-rate-aware demand (the ROADMAP item 3 substrate): the
        queue-math demand signal, scaled up by the worst serving-SLO
        burn rate from the monitor's LAST evaluation. The queue signal
        alone can sit at steady state while the error budget burns (a
        decode-tick regression serves the same backlog slower); a burn
        past 1.0 means the fleet is failing its objectives at current
        size, so demand multiplies by the burn (clamped to
        BURN_DEMAND_CAP — the autoscaler's step bound, not ours). A
        quiet burn leaves the base signal untouched, so scale-IN still
        follows the queue math. Callers evaluate() the monitor on their
        own cadence; this reads state, never the TSDB."""
        base = self.demand_replicas()
        burn = 0.0
        for state in monitor.describe():
            if state["name"] in slos:
                rates = state.get("burn_rates", {})
                if rates:
                    burn = max(burn, max(rates.values()))
        if burn <= 1.0:
            return base
        import math

        return max(base, math.ceil(base * min(burn, self.BURN_DEMAND_CAP)))

    # --------------------------------------------------------- reporting

    def ttft_percentiles(self) -> dict[str, float]:
        with self._mu:
            samples = sorted(self._ttfts)
        if not samples:
            return {"p50_s": 0.0, "p99_s": 0.0}
        return {
            "p50_s": samples[len(samples) // 2],
            "p99_s": samples[min(len(samples) - 1,
                                 int(len(samples) * 0.99))],
        }

    def snapshot(self) -> dict:
        """One coherent metrics view for /metrics and the load report."""
        with self._mu:
            m = dict(self.metrics)
        m["queue_depth"] = self.queue_depth()
        m["pending_tokens"] = self.pending_tokens()
        m["replicas_alive"] = len(self._alive())
        m["demand_replicas"] = self.demand_replicas()
        m["service_rate_tokens_per_s"] = round(self._rate, 3)
        m.update({f"ttft_{k}": round(v, 6)
                  for k, v in self.ttft_percentiles().items()})
        return m

    # --------------------------------------------------------- lifecycle

    def start(self) -> "FleetRouter":
        for r in self._alive():
            r.engine.start()
        return self

    def stop(self) -> None:
        for r in self._alive():
            r.engine.stop()

    def run_until_idle(self) -> None:
        """Synchronous drive (tests, the cpu-proxy scenario): round-robin
        one tick per live replica until every queue and row is empty."""
        while True:
            busy = False
            for r in self._alive():
                busy = r.engine.tick() or busy
            if not busy:
                return
