"""Fleet router — queue-depth-aware routing + SLO admission over N engines.

The tier between the activator and the ContinuousBatcher replicas
(ROADMAP item 2). One engine per InferenceService caps throughput at one
chip's decode bandwidth; the fleet runs N replica engines behind ONE
submit() surface with three production behaviors the solo engine lacks:

  - **least-loaded routing**: every submit lands on the replica with the
    smallest pending-token load (queued prompts + in-flight remaining
    budgets), not round-robin — a replica stuck behind a 4k-token prompt
    stops receiving traffic until it drains;
  - **SLO admission control**: estimated TTFT (pending tokens ahead /
    the fleet's observed service rate) beyond `ttft_slo_s` sheds the
    request with FleetOverloaded carrying a Retry-After hint — the same
    503 + Retry-After contract the activator already speaks, so clients
    (serving/client.py `_post`) re-dial on the server's schedule instead
    of piling onto a saturated fleet;
  - **zero-drop replica kill**: when a replica dies mid-flight, every
    request it was carrying — queued or decoding — is requeued onto a
    surviving replica via the engines' on_done callbacks; nothing is
    dropped, and `requeued_total` counts the disruption. Greedy rows
    re-decode to the identical tokens (engine exactness contract), so a
    requeue costs latency, never correctness.

The demand signal (`demand_replicas()`) is the autoscaler's input:
pending tokens over (service rate x TTFT SLO), clamped to at least the
alive replica count when queues are hot — the `kftpu_fleet_*` queue and
latency families in /metrics carry the same numbers for dashboards.

Paged-KV prefix reuse composes: hand each replica engine the SAME
PagedKVPool and a system prompt prefills once per fleet, not once per
replica admission (docs/serving.md).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from kubeflow_tpu.analysis.lockcheck import make_lock

#: EWMA weight of each completed request's observed decode rate
_RATE_ALPHA = 0.2

#: bound on the TTFT sample window backing the p50/p99 gauges
_TTFT_WINDOW = 512


class FleetOverloaded(RuntimeError):
    """Admission shed: the fleet cannot meet the TTFT SLO for this
    request. `retry_after_s` is the server-side hint the HTTP surfaces
    forward as a 503 Retry-After header."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


@dataclass
class Replica:
    """One engine slot in the fleet: the ContinuousBatcher plus the
    router's liveness view of it."""

    name: str
    engine: object
    alive: bool = True

    def pending_tokens(self) -> int:
        """The routing load signal: queued prompt+budget tokens plus the
        remaining budgets of in-flight rows. Best-effort reads of the
        ticker-private row table (same contract as the /metrics gauges —
        a mid-tick read is off by at most one row)."""
        eng = self.engine
        with eng._lock:
            queued = sum(ids.size + req.max_new_tokens
                         for ids, req in eng._queue)
        rows = sum(max(req.max_new_tokens - len(req.tokens), 1)
                   for req in eng._rows if req is not None)
        return queued + rows

    def depth(self) -> int:
        eng = self.engine
        with eng._lock:
            queued = len(eng._queue)
        return queued + sum(1 for r in eng._rows if r is not None)


@dataclass
class FleetRequest:
    """Router-level handle: survives replica kills (the engine handle it
    wraps is replaced on requeue). result() blocks for the tokens of the
    final successful attempt; TTFT is measured from fleet submission to
    the first token the CLIENT would have seen (requeues reset it —
    the wait is real)."""

    prompt: np.ndarray
    kwargs: dict
    t_submit: float
    replica: str = ""
    attempts: int = 0
    tokens: list = field(default_factory=list)
    t_first: float | None = None
    t_done: float | None = None
    error: str | None = None
    done: threading.Event = field(default_factory=threading.Event)
    on_token: object = None

    @property
    def ttft_s(self) -> float | None:
        return None if self.t_first is None else self.t_first - self.t_submit

    @property
    def tokens_per_s(self) -> float | None:
        if self.t_first is None or self.t_done is None:
            return None
        dt = self.t_done - self.t_first
        return len(self.tokens) / dt if dt > 0 else float("inf")

    def result(self, timeout: float | None = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("fleet request did not finish in time")
        if self.error is not None:
            raise RuntimeError(f"fleet request failed: {self.error}")
        return np.asarray(self.tokens, np.int32)


class FleetRouter:
    """N replica engines behind one submit() (module docstring)."""

    def __init__(self, replicas, ttft_slo_s: float = 0.0,
                 retry_after_s: float = 1.0,
                 service_rate_tokens_per_s: float = 0.0,
                 max_requeues: int = 3):
        """replicas: list of (name, ContinuousBatcher) or engines (named
        replica-<i>). ttft_slo_s: 0 disables admission shedding.
        service_rate_tokens_per_s: initial service-rate estimate; 0 defers
        admission control until the first completion calibrates it."""
        self.replicas: list[Replica] = []
        for i, r in enumerate(replicas):
            name, eng = r if isinstance(r, tuple) else (f"replica-{i}", r)
            self.replicas.append(Replica(name=name, engine=eng))
        if not self.replicas:
            raise ValueError("a fleet needs at least one replica")
        self.ttft_slo_s = float(ttft_slo_s)
        self.retry_after_s = float(retry_after_s)
        self.max_requeues = int(max_requeues)
        self._rate = float(service_rate_tokens_per_s)
        self._mu = make_lock("fleet.FleetRouter._mu")
        self._ttfts = collections.deque(maxlen=_TTFT_WINDOW)
        self.metrics = {
            "requests_admitted_total": 0,
            "requests_shed_total": 0,
            "requests_requeued_total": 0,
            "requests_completed_total": 0,
            "requests_failed_total": 0,
            "replica_kills_total": 0,
        }

    # ----------------------------------------------------------- routing

    def _alive(self) -> list[Replica]:
        return [r for r in self.replicas if r.alive]

    def load_view(self) -> dict[str, int]:
        """Per-replica pending-token load — the activator's queue-depth-
        aware endpoint pick reads this (serving/activator.py)."""
        return {r.name: r.pending_tokens() for r in self.replicas if r.alive}

    def queue_depth(self) -> int:
        return sum(r.depth() for r in self._alive())

    def pending_tokens(self) -> int:
        return sum(r.pending_tokens() for r in self._alive())

    def estimated_ttft_s(self, prompt_len: int) -> float | None:
        """Admission estimate: tokens ahead of this prompt's first token
        over the fleet's observed service rate. None until a completion
        has calibrated the rate (admission stays open — shedding on a
        guess would turn cold starts into outages)."""
        if self._rate <= 0.0:
            return None
        alive = self._alive()
        if not alive:
            return float("inf")
        ahead = min(r.pending_tokens() for r in alive) + prompt_len
        return ahead / self._rate

    def admit_or_raise(self, prompt_tokens: int) -> None:
        """The admission gate alone: raises FleetOverloaded when the
        estimated TTFT for `prompt_tokens` more prompt work exceeds the
        SLO. Callers submitting a BATCH gate once with the batch total
        (then submit ungated) so a shed can never orphan half-admitted
        rows on the fleet."""
        est = self.estimated_ttft_s(prompt_tokens)
        if self.ttft_slo_s > 0.0 and est is not None \
                and est > self.ttft_slo_s:
            with self._mu:
                self.metrics["requests_shed_total"] += 1
            raise FleetOverloaded(
                f"estimated TTFT {est:.3f}s exceeds SLO "
                f"{self.ttft_slo_s:.3f}s", retry_after_s=max(
                    self.retry_after_s,
                    min(est - self.ttft_slo_s, 30.0)))

    def submit(self, prompt_ids, gate: bool = True,
               **kwargs) -> FleetRequest:
        """Admission-gate then route to the least-loaded live replica.
        Raises FleetOverloaded (with retry_after_s) on shed — including
        when no replica is alive, counted as a shed, never as an
        admission."""
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if gate:
            self.admit_or_raise(ids.size)
        on_token = kwargs.pop("on_token", None)
        freq = FleetRequest(prompt=ids, kwargs=dict(kwargs),
                            t_submit=time.perf_counter(),
                            on_token=on_token)
        try:
            self._dispatch(freq)
        except FleetOverloaded:
            with self._mu:
                self.metrics["requests_shed_total"] += 1
            raise
        # counted only once the request is really on a replica, so
        # admitted == completed + failed + in-flight always holds
        with self._mu:
            self.metrics["requests_admitted_total"] += 1
        return freq

    def _pick(self) -> Replica:
        alive = self._alive()
        if not alive:
            raise FleetOverloaded("no live replicas",
                                  retry_after_s=self.retry_after_s)
        return min(alive, key=lambda r: r.pending_tokens())

    def _dispatch(self, freq: FleetRequest) -> None:
        # the fleet handle rides INSIDE the engine callbacks (partial
        # binding) — a registry keyed on the engine handle would race the
        # replica's ticker, which can emit tokens between submit() and
        # any later registration. The pick AND the enqueue happen under
        # _mu, ordered against kill_replica's alive=False flip (also
        # under _mu): either this dispatch lands before the kill — and
        # the kill's _fail_all requeues it — or the pick already excludes
        # the corpse. Without the ordering, an enqueue racing the kill
        # strands the request on a stopped ticker's queue forever.
        from functools import partial

        with self._mu:
            rep = self._pick()
            freq.replica = rep.name
            freq.attempts += 1
            rep.engine.submit(
                freq.prompt, on_token=partial(self._on_token, freq),
                on_done=partial(self._on_done, freq), **freq.kwargs)

    # --------------------------------------------- engine-thread callbacks

    def _on_token(self, freq: FleetRequest, handle, tok: int) -> None:
        if freq.done.is_set():
            return
        if freq.t_first is None:
            freq.t_first = time.perf_counter()
        freq.tokens.append(tok)
        if freq.on_token is not None:
            freq.on_token(freq, tok)

    def _on_done(self, freq: FleetRequest, handle) -> None:
        """Runs on the finishing replica's engine thread. Success
        completes the fleet handle; a replica-death failure requeues onto
        a survivor — the zero-drop contract."""
        if freq.done.is_set():
            return
        if handle.error is None:
            freq.tokens = [int(t) for t in handle.tokens]
            freq.t_done = time.perf_counter()
            with self._mu:
                self.metrics["requests_completed_total"] += 1
                if freq.ttft_s is not None:
                    self._ttfts.append(freq.ttft_s)
                self._observe_rate(freq)
            freq.done.set()
            return
        if freq.attempts > self.max_requeues:
            freq.error = f"gave up after {freq.attempts} attempts: " \
                         f"{handle.error}"
            with self._mu:
                self.metrics["requests_failed_total"] += 1
            freq.done.set()
            return
        # replica died (or poisoned round): start over on a survivor.
        # Partial tokens are discarded — greedy decode reproduces them
        # exactly; TTFT restarts because the client's wait does too.
        freq.tokens = []
        freq.t_first = None
        with self._mu:
            self.metrics["requests_requeued_total"] += 1
        try:
            self._dispatch(freq)
        except FleetOverloaded as exc:
            freq.error = str(exc)
            with self._mu:
                self.metrics["requests_failed_total"] += 1
            freq.done.set()

    def _observe_rate(self, freq: FleetRequest) -> None:
        """EWMA of completed requests' end-to-end token rate — PROMPT +
        output tokens over client-experienced wall time, the same unit
        pending_tokens() counts (queued prompts + budgets). Mixing units
        here would inflate estimated TTFT by the prompt/output ratio and
        shed long-prompt traffic the fleet could comfortably serve.
        Caller holds _mu."""
        wall = (freq.t_done or 0.0) - freq.t_submit
        if wall <= 0.0:
            return
        rate = (freq.prompt.size + len(freq.tokens)) / wall
        self._rate = (rate if self._rate <= 0.0
                      else (1 - _RATE_ALPHA) * self._rate
                      + _RATE_ALPHA * rate)

    @property
    def service_rate_tokens_per_s(self) -> float:
        return self._rate

    # ------------------------------------------------------------ chaos

    def kill_replica(self, name_or_idx) -> Replica:
        """Chaos entry (the drills' mid-run kill): stop the replica's
        ticker and fail everything it carries — the on_done callbacks
        requeue every request onto the survivors."""
        rep = (self.replicas[name_or_idx]
               if isinstance(name_or_idx, int)
               else next(r for r in self.replicas
                         if r.name == name_or_idx))
        with self._mu:
            # ordered against _dispatch (also under _mu): any dispatch
            # that won the race has ALREADY enqueued, so the _fail_all
            # below requeues it; later picks exclude the corpse
            rep.alive = False
            self.metrics["replica_kills_total"] += 1
        rep.engine.stop()
        rep.engine._fail_all("replica killed")
        return rep

    def add_replica(self, engine, name: str = "") -> Replica:
        """Scale-out entry (the autoscaler's add path)."""
        rep = Replica(name=name or f"replica-{len(self.replicas)}",
                      engine=engine)
        self.replicas.append(rep)
        return rep

    # ------------------------------------------------------- autoscaling

    def demand_replicas(self) -> int:
        """Desired replica count from the queue/latency signal: pending
        tokens over what ONE replica can serve inside the TTFT SLO (the
        EWMA service rate is a per-request — i.e. per-replica-queue —
        rate, so it is NOT divided by the alive count: demand must
        depend on the backlog, not on how many replicas currently exist,
        or scale-out would raise its own demand signal). The floor is
        the number of BUSY replicas (scale-in only below actual use);
        the ceiling is the autoscaler's call."""
        alive = self._alive()
        busy = sum(1 for r in alive if r.depth() > 0)
        per_replica = self._rate * self.ttft_slo_s
        if per_replica <= 0.0:
            return max(1, busy)
        import math

        return max(1, busy, math.ceil(self.pending_tokens() / per_replica))

    # --------------------------------------------------------- reporting

    def ttft_percentiles(self) -> dict[str, float]:
        with self._mu:
            samples = sorted(self._ttfts)
        if not samples:
            return {"p50_s": 0.0, "p99_s": 0.0}
        return {
            "p50_s": samples[len(samples) // 2],
            "p99_s": samples[min(len(samples) - 1,
                                 int(len(samples) * 0.99))],
        }

    def snapshot(self) -> dict:
        """One coherent metrics view for /metrics and the load report."""
        with self._mu:
            m = dict(self.metrics)
        m["queue_depth"] = self.queue_depth()
        m["pending_tokens"] = self.pending_tokens()
        m["replicas_alive"] = len(self._alive())
        m["demand_replicas"] = self.demand_replicas()
        m["service_rate_tokens_per_s"] = round(self._rate, 3)
        m.update({f"ttft_{k}": round(v, 6)
                  for k, v in self.ttft_percentiles().items()})
        return m

    # --------------------------------------------------------- lifecycle

    def start(self) -> "FleetRouter":
        for r in self._alive():
            r.engine.start()
        return self

    def stop(self) -> None:
        for r in self._alive():
            r.engine.stop()

    def run_until_idle(self) -> None:
        """Synchronous drive (tests, the cpu-proxy scenario): round-robin
        one tick per live replica until every queue and row is empty."""
        while True:
            busy = False
            for r in self._alive():
                busy = r.engine.tick() or busy
            if not busy:
                return
