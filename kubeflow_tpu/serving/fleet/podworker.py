"""Pod worker — one ContinuousBatcher behind a wire socket.

``python -m kubeflow_tpu.serving.fleet.podworker`` is the serving tier's
real process boundary: the fleet spawns one of these per replica
(podclient.spawn_pod), each hosting its own model, paged-KV pool, and
engine, reachable only through the length-prefixed JSON protocol in
wire.py — over AF_UNIX (single-host, the PR-15 wire) or TCP
(KFTPU_POD_TRANSPORT=tcp: bind 127.0.0.1:0, write the kernel-chosen
port atomically to KFTPU_POD_NET_PORT_FILE, and echo it back through
the hello so the dial side can cross-check discovery). The worker is deliberately SINGLE-THREADED — one connection,
one verb at a time, engine ticks driven by the client's `tick` verb —
so the process owns no locks and a SIGKILL can never leave a
half-updated shared structure behind; all cross-request state the
router needs to survive a kill lives on the CLIENT side (the router's
token record), which is exactly the zero-drop contract.

Env contract (utils/envvars.py): KFTPU_POD_SOCKET (bind path),
KFTPU_POD_NAME (trace service / heartbeat identity), KFTPU_POD_SPEC
(JSON engine spec), plus the existing pod contract — KFTPU_TRACE_DIR /
KFTPU_TRACEPARENT ride through tracing.init_worker_from_env so a dead
pod's spans still land in /debug/trace, and KFTPU_HEARTBEAT_FILE arms
the per-tick liveness beat the router's hang watch consumes (SIGSTOP =
alive-but-silent, detectable only by heartbeat age).

Delivery reliability: every token/done event enters a monotonic-id
OUTBOX and is re-sent on every tick reply until the client's cumulative
ack prunes it — a torn frame or connection reset loses no tokens, it
just redelivers (the client dedups by event id). Submits are idempotent
by request id for the same reason. Backpressure is HTTP-shaped: a full
queue answers 503 with retry_after_s, an expired propagated deadline
answers 504 — the client's retry policy (utils/retry) honors both.

Epoch fencing (the TCP failure family): every envelope carries the
sender's fence epoch. A hello with a HIGHER epoch adopts it (the
scaler's replacement taking over the replica identity); any frame with
a LOWER epoch than the adopted one answers 410 — a partitioned client
that resurfaces after its replacement attached can neither submit nor
tick, so a partition heal can never produce two replicas serving the
same rid. The refusal is symmetric: the client fences itself on the
first 410 and refuses the worker's late acks/tokens (podclient.py).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import time

from kubeflow_tpu.analysis.protocheck.eventlog import log_event
from kubeflow_tpu.serving.fleet.wire import (
    CODE_BAD_REQUEST,
    CODE_BUSY,
    CODE_CONFLICT,
    CODE_DEADLINE,
    CODE_FENCED,
    CODE_INTERNAL,
    EV_DONE,
    EV_TOKEN,
    F_ACK,
    F_CHAIN,
    F_DEADLINE_S,
    F_DYING,
    F_EOS,
    F_EPOCH,
    F_ERROR,
    F_EV,
    F_ID,
    F_KEEP_CHAIN,
    F_MAX_NEW_TOKENS,
    F_N,
    F_PROMPT,
    F_RESUME,
    F_RESUMED,
    F_RID,
    F_SEQ,
    F_TEMPERATURE,
    F_TOK,
    F_TOKENS,
    F_VERB,
    PodWireError,
    error_reply,
    ok_reply,
    recv_frame,
    send_frame,
    serialize_chain,
)
from kubeflow_tpu.utils.envvars import (
    ENV_POD_NAME,
    ENV_POD_PORT_FILE,
    ENV_POD_SOCKET,
    ENV_POD_SPEC,
    ENV_POD_TRANSPORT,
)


class PodServer:
    """The worker-side protocol state machine around one engine."""

    def __init__(self, name: str, spec: dict, tracer=None):
        self.name = name
        self.spec = spec
        self.tracer = tracer
        self._events: list[dict] = []        # outbox, pruned by acks
        self._next_event_id = 1
        self._seen_rids: set[str] = set()    # submit idempotency
        self._dying: str | None = None       # poisoned-engine reason
        self._epoch = 0                      # adopted fence epoch
        self._port: int | None = None        # bound TCP port (tcp only)
        self.engine, self.pool = self._build_engine()
        from kubeflow_tpu.health import HeartbeatWriter

        self.hb = HeartbeatWriter.from_env()
        self._warmup()

    # ------------------------------------------------------------ build

    def _build_engine(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models.gpt import GPTConfig, GPTLM
        from kubeflow_tpu.serving.continuous import ContinuousBatcher
        from kubeflow_tpu.serving.fleet.pagedkv import PagedKVPool

        spec = self.spec
        cfg = GPTConfig(**spec["model"])
        model = GPTLM(cfg)
        # deterministic weights from the spec's init seed and a FIXED
        # init shape: every pod of a fleet builds byte-identical
        # parameters from the spec alone — no weight shipping
        variables = jax.jit(model.init)(
            jax.random.PRNGKey(int(spec.get("init_seed", 0))),
            jnp.zeros((1, min(8, cfg.max_len)), jnp.int32))
        pool_spec = spec.get("pool") or {}
        pool = PagedKVPool(
            block_size=int(pool_spec.get("block_size", 8)),
            capacity_blocks=int(pool_spec.get("capacity_blocks", 1024)))
        eng = ContinuousBatcher(
            model, variables,
            max_rows=int(spec.get("max_rows", 4)),
            default_max_new_tokens=int(
                spec.get("default_max_new_tokens", 32)),
            eos_token_id=spec.get("eos_token_id"),
            seed=int(spec.get("seed", 0)),
            prefill_chunk=int(spec.get("prefill_chunk", 0)),
            paged_kv=pool,
            block_budget=bool(spec.get("block_budget", False)),
            max_chunks_per_tick=int(spec.get("max_chunks_per_tick", 1)),
            tracer=(self.tracer
                    if getattr(self.tracer, "enabled", False) else None),
        )
        repeats = int(spec.get("chaos_decode_repeats", 1))
        if repeats > 1:
            # the cpu-proxy gate's decode chaos, armed in-process from
            # the spec (NOT the env: the controller decides per fleet)
            from kubeflow_tpu.profiling.cpu_proxy import _arm_decode_chaos

            _arm_decode_chaos([eng], repeats)
        return eng, pool

    def _warmup(self) -> None:
        """Compile every executable the serve phase dispatches BEFORE
        the socket goes live — the gate measures serving, not XLA."""
        import numpy as np

        prompts = self.spec.get("warmup_prompts") or []
        new_toks = int(self.spec.get("warmup_new_tokens", 2))
        repeats = int(self.spec.get("warmup_repeats", 2))
        for prompt in prompts:
            ids = np.asarray(prompt, np.int32)
            for _ in range(max(repeats, 1)):
                self.engine.submit(ids, max_new_tokens=new_toks)
                self.engine.run_until_idle()
        if self.spec.get("warmup_resume") and prompts:
            # the decode-leg shapes: keep_chain retire (chain-append
            # extraction window) and the resume-admission splice — every
            # handoff dispatch hits both, so compile them before the
            # socket goes live
            ids = np.asarray(prompts[0], np.int32)
            req = self.engine.submit(ids, max_new_tokens=new_toks,
                                     keep_chain=True)
            self.engine.run_until_idle()
            chain = getattr(req, "chain", None)
            if chain is not None and not chain.frozen:
                keep = int(chain.length) - int(ids.size) + 1
                if 0 < keep <= len(req.tokens) and keep < new_toks:
                    req.chain = None
                    self.engine.submit(
                        ids, max_new_tokens=new_toks,
                        resume_from=(chain, [int(t) for t
                                             in req.tokens[:keep]]))
                    self.engine.run_until_idle()
                else:
                    chain.release()
                    req.chain = None

    # ----------------------------------------------------------- events

    def _emit(self, ev: dict) -> None:
        ev[F_ID] = self._next_event_id
        self._next_event_id += 1
        self._events.append(ev)
        log_event("wire", "worker", "emit", id=ev[F_ID],
                  kind=ev.get(F_EV), rid=ev.get(F_RID), pid=os.getpid())

    def _on_token(self, req, tok: int) -> None:
        self._emit({F_EV: EV_TOKEN, F_RID: req.request_id,
                    F_TOK: int(tok)})

    def _on_done(self, req) -> None:
        ev = {
            F_EV: EV_DONE,
            F_RID: req.request_id,
            F_ERROR: req.error,
            F_TOKENS: [int(t) for t in req.tokens],
            F_RESUMED: bool(req.resumed),
            "ttft_s": req.ttft_s,
            "tps": req.tokens_per_s,
            F_CHAIN: None,
        }
        chain = getattr(req, "chain", None)
        if chain is not None and chain.refs and not chain.frozen:
            # keep_chain retire: the finished chain crosses the wire as
            # serialized blocks; the local refs release immediately —
            # the payload carries everything the adopter needs
            ev[F_CHAIN] = serialize_chain(self.pool, chain.refs)
        if chain is not None:
            chain.release()
            req.chain = None
        self._emit(ev)

    # ------------------------------------------------------------ verbs

    def handle(self, env: dict) -> dict:
        seq = int(env.get(F_SEQ, 0))
        verb = env.get(F_VERB, "")
        deadline_s = env.get(F_DEADLINE_S)
        if deadline_s is not None and float(deadline_s) <= 0.0:
            return error_reply(seq, CODE_DEADLINE,
                               f"deadline expired before {verb!r}")
        # fence gate: stale epochs are refused on EVERY verb — a
        # presumed-dead client resurfacing after its replacement adopted
        # a higher epoch can neither submit nor tick (410, terminal on
        # the client side). A hello with a higher epoch is the adoption
        # itself (done in _verb_hello so its echo carries the result).
        env_epoch = int(env.get(F_EPOCH, 0))
        if env_epoch < self._epoch:
            log_event("wire", "worker", "refuse_stale",
                      env_epoch=env_epoch, epoch=self._epoch, verb=verb,
                      pid=os.getpid())
            return error_reply(
                seq, CODE_FENCED,
                f"stale epoch {env_epoch} < {self._epoch}: "
                f"{verb!r} refused (fenced)")
        fn = getattr(self, f"_verb_{verb}", None)
        if fn is None:
            return error_reply(seq, CODE_BAD_REQUEST,
                               f"unknown verb {verb!r}")
        try:
            return fn(seq, env)
        except Exception as e:  # noqa: BLE001 — protocol boundary
            return error_reply(seq, CODE_INTERNAL,
                               f"{type(e).__name__}: {e}")

    def _verb_hello(self, seq: int, env: dict) -> dict:
        eng = self.engine
        # epoch adoption: handle() already refused anything stale, so
        # this hello is the newest claimant — adopt its epoch and echo
        # it (with the bound TCP port) so the dial side can cross-check
        # discovery against what the worker actually serves
        env_epoch = int(env.get(F_EPOCH, 0))
        purged = False
        if env_epoch > self._epoch:
            # a STRICTLY newer claim starts from a clean slate: the
            # superseded claim's undelivered events and rid-dedup
            # entries must never leak into the successor's streams (a
            # same-epoch hello is a reconnect of the same claim, where
            # redelivery IS the replay contract — keep everything)
            self._events.clear()
            self._seen_rids.clear()
            purged = True
        log_event("wire", "worker", "adopt", old=self._epoch,
                  new=max(self._epoch, env_epoch), purged=purged,
                  pid=os.getpid())
        self._epoch = max(self._epoch, env_epoch)
        return ok_reply(
            seq, name=self.name, pid=os.getpid(),
            default_max_new_tokens=eng.default_max_new_tokens,
            eos_token_id=(list(eng.eos_token_id)
                          if eng.eos_token_id else None),
            block_size=self.pool.block_size,
            epoch=self._epoch, port=self._port)

    def _depth(self) -> int:
        eng = self.engine
        return (len(eng._queue)
                + sum(1 for r in eng._rows if r is not None))

    def _verb_submit(self, seq: int, env: dict) -> dict:
        import numpy as np

        from kubeflow_tpu.serving.fleet.wire import deserialize_chain

        if self._dying is not None:
            return error_reply(seq, CODE_INTERNAL,
                               f"engine poisoned: {self._dying}")
        rid = str(env.get(F_RID, ""))
        if rid and rid in self._seen_rids:
            # redelivery after a torn ack: the original submit landed
            log_event("wire", "worker", "dup_submit", rid=rid,
                      pid=os.getpid())
            return ok_reply(seq, dup=True, depth=self._depth())
        max_queue = int(self.spec.get("max_queue", 0))
        if max_queue and len(self.engine._queue) >= max_queue:
            return error_reply(seq, CODE_BUSY, "queue full",
                               retry_after_s=0.05)
        resume = None
        if env.get(F_RESUME) is not None:
            chain = deserialize_chain(self.pool, env[F_RESUME][F_CHAIN])
            if chain.frozen:
                # the receiving pool could not cover every position
                # (covered-by-sibling) — refuse rather than resume on
                # silently wrong K/V; the client falls back to scratch
                chain.release()
                return error_reply(
                    seq, CODE_CONFLICT, "resume chain frozen on re-insert")
            resume = (chain, [int(t) for t in env[F_RESUME][F_TOKENS]])
        req = self.engine.submit(
            np.asarray(env[F_PROMPT], np.int32),
            max_new_tokens=env.get(F_MAX_NEW_TOKENS),
            eos_token_id=env.get(F_EOS),
            temperature=float(env.get(F_TEMPERATURE, 0.0)),
            on_token=self._on_token,
            on_done=self._on_done,
            request_id=rid,
            keep_chain=bool(env.get(F_KEEP_CHAIN, False)),
            resume_from=resume)
        # request_id normally only sticks under an armed tracer; the
        # event stream is keyed by it, so pin it unconditionally
        req.request_id = rid
        if rid:
            self._seen_rids.add(rid)
        return ok_reply(seq, depth=self._depth())

    def _verb_tick(self, seq: int, env: dict) -> dict:
        ack = int(env.get(F_ACK, 0))
        if ack:
            self._events = [e for e in self._events if e[F_ID] > ack]
        busy = False
        n = max(int(env.get(F_N, 1)), 1)
        if self._dying is None:
            try:
                for _ in range(n):
                    busy = self.engine.tick()
                    if not busy:
                        break
            except Exception as e:  # noqa: BLE001 — poisoned engine
                self._dying = f"{type(e).__name__}: {e}"
                self.engine._fail_all(
                    f"worker tick failed: {self._dying}")
                busy = False
        if self.hb is not None:
            self.hb.beat(step=self.engine.step_count, phase="serve")
        if self.tracer is not None and getattr(self.tracer, "enabled",
                                               False):
            from kubeflow_tpu.tracing.core import flush

            # idempotent per-pid file: a SIGKILL between flushes loses
            # at most one tick batch of spans, never the file
            flush(self.tracer)
        eng = self.engine
        return ok_reply(
            seq, events=list(self._events), busy=busy,
            depth=self._depth(), step_count=eng.step_count,
            prefill_tokens_total=eng.prefill_tokens_total,
            prefill_tokens_reused=eng.prefill_tokens_reused,
            tick_error=self._dying)

    def _verb_drain(self, seq: int, env: dict) -> dict:
        return ok_reply(seq, depth=self._depth())

    def _verb_heartbeat(self, seq: int, env: dict) -> dict:
        if self.hb is not None:
            self.hb.beat(step=self.engine.step_count, phase="serve")
        return ok_reply(seq, pid=os.getpid())

    def _verb_kill(self, seq: int, env: dict) -> dict:
        return ok_reply(seq, dying=True)

    # ------------------------------------------------------------ serve

    def serve(self, sock_path: str, transport: str = "unix",
              port_file: str | None = None) -> None:
        if transport == "tcp":
            # multi-host wire: bind loopback on a kernel-chosen port and
            # publish it ATOMICALLY (write-then-rename) — the dial side
            # polls the port file the way it polls the AF_UNIX socket
            # path, and a torn partial write must never read as a port
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("127.0.0.1", 0))
            self._port = int(srv.getsockname()[1])
            if port_file:
                tmp = f"{port_file}.{os.getpid()}.tmp"
                with open(tmp, "w", encoding="utf-8") as fh:
                    fh.write(str(self._port))
                os.replace(tmp, port_file)
        else:
            try:
                os.unlink(sock_path)
            except OSError:
                pass
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(sock_path)
        srv.listen(1)
        if self.hb is not None:
            self.hb.beat(step=0, phase="serve")
        while True:
            conn, _addr = srv.accept()
            try:
                self._serve_conn(conn)
            except (PodWireError, OSError):
                pass  # client went away: re-accept (the client redials)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve_conn(self, conn: socket.socket) -> None:
        while True:
            env = recv_frame(conn)
            reply = self.handle(env)
            send_frame(conn, reply)
            if reply.get(F_DYING):
                if (self.tracer is not None
                        and getattr(self.tracer, "enabled", False)):
                    from kubeflow_tpu.tracing.core import flush

                    flush(self.tracer)
                conn.close()
                os._exit(0)


def _arm_orphan_watchdog() -> None:
    """A pod must never outlive its spawner. The client process owns the
    lifecycle, but a SIGKILLed spawner (a timed-out test runner, an OOM
    kill) runs no teardown — without this, the worker parks on accept()
    forever. PR_SET_PDEATHSIG asks the kernel to SIGKILL this process
    the moment the spawning thread exits; Linux-only, best-effort."""
    if sys.platform != "linux":
        return
    try:
        import ctypes
        import signal as _signal

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, _signal.SIGKILL, 0, 0, 0)  # 1 = PR_SET_PDEATHSIG
    except (OSError, AttributeError, TypeError):
        return
    # close the arming race: the parent may have died between fork and
    # prctl, in which case we are already reparented and no signal comes
    if os.getppid() == 1:
        os._exit(0)


def main() -> int:
    _arm_orphan_watchdog()
    # the axon sitecustomize force-registers the TPU plugin in every
    # interpreter; a config update (which wins over env) is required to
    # actually get CPU (same reasoning as tests/conftest.py)
    import jax

    jax.config.update("jax_platforms", "cpu")
    name = os.environ.get(ENV_POD_NAME, "pod")
    transport = os.environ.get(ENV_POD_TRANSPORT, "unix")
    sock_path = os.environ.get(ENV_POD_SOCKET, "")
    port_file = os.environ.get(ENV_POD_PORT_FILE) or None
    if transport != "tcp" and not sock_path:
        raise KeyError(ENV_POD_SOCKET)
    with open(os.environ[ENV_POD_SPEC], encoding="utf-8") as fh:
        spec = json.load(fh)
    if spec.get("compile_cache_dir"):
        # inference-only programs are safe under the persistent cache
        # (the tests/conftest.py corruption vector needs a resumed fit
        # loop) and every pod of a fleet compiles the SAME executables
        from kubeflow_tpu.utils.compile_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache(spec["compile_cache_dir"])
    from kubeflow_tpu.tracing.core import init_worker_from_env

    tracer = init_worker_from_env(service=name)
    t0 = time.perf_counter()
    server = PodServer(name, spec, tracer=tracer)
    print(f"[podworker {name}] ready in {time.perf_counter() - t0:.2f}s "
          f"pid={os.getpid()}", file=sys.stderr, flush=True)
    server.serve(sock_path, transport=transport, port_file=port_file)
    return 0


if __name__ == "__main__":
    sys.exit(main())
