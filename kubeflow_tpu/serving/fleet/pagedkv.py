"""Paged/block KV cache — the single KV substrate for the request lifetime.

Decode is HBM-bandwidth-bound, but PREFILL is compute-bound and scales
with prompt length — and production prompts share long prefixes (the
system prompt, few-shot preambles). The pool pages the K/V store the way
vLLM does, with one TPU-idiomatic twist: the in-engine decode buffer
stays a fixed-row static-shape scratch window (serving/continuous.py —
the shape XLA wants), while the POOL is the store of record for the
whole request lifetime. Prompt blocks land at admission; decode rows
append each generated position's K/V into their chain as they go
(``SequenceChain`` — allocate-on-boundary, COW preserved), so:

  - memory accounting is working-set-proportional: admission can be
    block-budgeted (``available_blocks``) instead of slot-budgeted;
  - a finished prefill's chain can be ADOPTED by another replica
    (``adopt``/``gather`` — the disaggregated prefill/decode handoff);
  - a replica killed mid-decode leaves its chain behind, and the requeue
    RESUMES from the surviving blocks instead of re-decoding from
    scratch (router.py);
  - a follow-on conversation turn whose prompt is the previous prompt +
    completion matches deep into the GENERATED chain, not just the old
    prompt.

The original prefix-reuse contract is unchanged:

  - prompts split into fixed-size BLOCKS (`block_size` tokens); each
    fully-prefilled block's K/V (every layer, rope-rotated, position
    [p0, p1)) is stored once, keyed by the CHAIN HASH of its content —
    sha1(parent_digest + token bytes) — so block identity encodes the
    whole prefix, not just the block's own tokens;
  - the block table is REFCOUNTED: a sequence holds references to the
    blocks its prompt maps to from admission to retire, and eviction
    (LRU, leaf-first) only ever removes unreferenced blocks;
  - divergence is COPY-ON-WRITE: blocks are immutable — two prompts that
    split mid-block simply stop matching at the split, and extending a
    shared partial tail block allocates a new block (``cow_copies``)
    instead of mutating the one the other sequence still references.

On admission the engine asks ``match(ids)``: the longest cached chain
comes back as gathered per-layer K/V, is written into the row cache at
positions [0, shared) with ``cache_index``/``pos_index`` seeded to
`shared`, and the model prefills ONLY the suffix — the per-row index
machinery models/gpt.py keeps for continuous batching makes the seeded
row indistinguishable from one the model prefilled itself. Position
alignment makes the reuse exact: cached K carries its absolute-position
rotation, and a prompt prefix always sits at positions [0, L).

Host-side numpy on purpose: the pool is the fleet tier's shared store
(N engines on N threads hit one pool under one lock), and the arrays
only cross to the device inside the admitting engine's jitted prefill.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.analysis.protocheck.eventlog import log_event

#: digest of the empty prefix — the chain root every block hangs off
ROOT = b"kftpu-fleet-root"


def _digest(parent: bytes, ids: np.ndarray) -> bytes:
    return hashlib.sha1(parent + ids.astype(np.int32).tobytes()).digest()


# --------------------------------------------------- cache-pytree helpers


def _walk(tree, prefix=""):
    """Yield (path, leaf) for every array leaf of a nested-dict cache
    pytree — the flax cache collection is plain dicts, so a stable
    '/'-joined key path is enough to pair extract with seed."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _walk(tree[k], f"{prefix}/{k}")
    else:
        yield prefix, tree


def extract_prompt_kv(row_cache, length: int) -> dict[str, np.ndarray]:
    """Per-position K/V of a batch-1 row cache's first `length` positions:
    {leaf path -> (length, kv_heads, head_dim) np array} for every
    cached_key/cached_value leaf. The pool stores slices of these."""
    out: dict[str, np.ndarray] = {}
    for path, leaf in _walk(row_cache):
        name = path.rsplit("/", 1)[-1]
        if name in ("cached_key", "cached_value"):
            out[path] = np.asarray(leaf)[0, :length].copy()
    return out


def make_row_template(live_cache) -> dict:
    """Batch-1 zeroed np twin of the engine's live cache pytree — the
    starting point for a seeded (prefix-reused) or chunked prefill."""

    def zero(tree):
        if isinstance(tree, dict):
            return {k: zero(v) for k, v in tree.items()}
        a = np.asarray(tree)
        return np.zeros((1,) + a.shape[1:], a.dtype)

    return zero(live_cache)


def seed_row_cache(template: dict, kv: dict[str, np.ndarray],
                   shared: int) -> dict:
    """Fresh batch-1 row cache with the pool's gathered K/V written at
    positions [0, shared) and every cache_index/pos_index leaf set to
    `shared` — exactly the state a one-shot prefill of those tokens
    leaves behind, so the suffix prefill continues seamlessly."""

    def build(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: build(v, f"{prefix}/{k}") for k, v in tree.items()}
        name = prefix.rsplit("/", 1)[-1]
        if name in ("cache_index", "pos_index"):
            return np.full_like(tree, shared)
        got = kv.get(prefix)
        if got is None:
            return tree.copy()
        buf = tree.copy()
        buf[0, :shared] = got[:shared]
        return buf

    return build(template)


# ------------------------------------------------------------------ pool


@dataclass
class _Block:
    digest: bytes
    parent: bytes
    ids: np.ndarray                      # (n,) int32, n <= block_size
    kv: dict[str, np.ndarray]            # path -> (n, kvh, d)
    full: bool
    refcount: int = 0
    last_used: int = 0
    children: set = field(default_factory=set)


@dataclass
class PrefixMatch:
    """Result of PagedKVPool.match: `length` cached positions, gathered
    K/V per leaf path, and the block refs the caller now holds (release
    via PagedKVPool.release when the sequence retires)."""

    length: int
    kv: dict[str, np.ndarray]
    blocks: list[bytes]


class PagedKVPool:
    """Refcounted block table over prompt-prefix K/V (module docstring)."""

    def __init__(self, block_size: int = 8, capacity_blocks: int = 1024):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if capacity_blocks < 1:
            raise ValueError(
                f"capacity_blocks must be >= 1, got {capacity_blocks}")
        self.block_size = int(block_size)
        self.capacity_blocks = int(capacity_blocks)
        self._table: dict[bytes, _Block] = {}
        self._clock = 0
        #: blocks with refcount > 0, maintained incrementally on every
        #: 0<->1 transition (_ref/_unref/_drop) — the block-budgeted
        #: admission gate and the blocks_in_use gauge read it O(1)
        #: instead of scanning the table under the shared pool lock on
        #: every admission attempt
        self._pinned = 0
        self._mu = make_lock("fleet.PagedKVPool._mu")
        self.metrics = {
            "blocks_cached": 0,
            "blocks_evicted_total": 0,
            "blocks_reused_total": 0,
            "tokens_reused_total": 0,
            "cow_copies_total": 0,
        }

    # ------------------------------------------------------- block budget

    def blocks_in_use(self) -> int:
        """Blocks some live sequence still references — the pinned
        working set (the ``kftpu_fleet_kv_blocks_in_use`` gauge).
        Unreferenced cached blocks are reuse inventory, not use: they
        evict on demand."""
        with self._mu:
            return self._pinned

    def available_blocks(self) -> int:
        """Blocks a new sequence could claim right now: capacity minus
        the pinned working set (cached-but-unreferenced blocks evict on
        demand, so they count as available). The engine's block-budgeted
        admission gate reads this instead of counting row slots."""
        with self._mu:
            return max(self.capacity_blocks - self._pinned, 0)

    def _ref(self, blk: _Block) -> None:
        """Acquire one reference under self._mu (the ONE increment
        path: keeps the pinned counter exact on 0->1)."""
        blk.refcount += 1
        if blk.refcount == 1:
            self._pinned += 1
        blk.last_used = self._clock

    def _unref(self, blk: _Block) -> None:
        """Drop one reference under self._mu (exact on 1->0)."""
        if blk.refcount > 0:
            blk.refcount -= 1
            if blk.refcount == 0:
                self._pinned -= 1

    # ------------------------------------------------------------- match

    def match(self, ids) -> PrefixMatch:
        """Longest cached prefix of `ids`: full-block chain first, then at
        most one partial tail block whose content is a prefix of the
        remainder. Acquires one reference per matched block."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        with self._mu:
            self._clock += 1
            parent = ROOT
            blocks: list[_Block] = []
            pos = 0
            while pos + self.block_size <= ids.size:
                d = _digest(parent, ids[pos:pos + self.block_size])
                blk = self._table.get(d)
                if blk is None or not blk.full:
                    break
                blocks.append(blk)
                parent = d
                pos += self.block_size
            # partial tail: the longest child of the last matched block
            # whose tokens prefix the remaining ids (COW keeps several
            # divergent partials alive side by side — pick the best)
            tail: _Block | None = None
            rest = ids[pos:]
            siblings = (self._root_children() if parent == ROOT
                        else self._table[parent].children)
            for child_d in list(siblings):
                child = self._table.get(child_d)
                if child is None or child.full or child.ids.size > rest.size:
                    continue
                if np.array_equal(child.ids, rest[:child.ids.size]) and (
                        tail is None or child.ids.size > tail.ids.size):
                    tail = child
            if tail is not None:
                blocks.append(tail)
                pos += tail.ids.size
            for blk in blocks:
                self._ref(blk)
            if blocks:
                log_event("kv", "pool", "publish",
                          digests=[b.digest.hex() for b in blocks],
                          rcs=[b.refcount for b in blocks])
            kv: dict[str, np.ndarray] = {}
            if blocks:
                for path in blocks[0].kv:
                    kv[path] = np.concatenate(
                        [b.kv[path] for b in blocks], axis=0)
                self.metrics["blocks_reused_total"] += len(blocks)
                self.metrics["tokens_reused_total"] += pos
            return PrefixMatch(length=pos, kv=kv,
                               blocks=[b.digest for b in blocks])

    def _root_children(self):
        return [d for d, b in self._table.items() if b.parent == ROOT]

    # ------------------------------------------------------------ insert

    def insert(self, ids, kv: dict[str, np.ndarray]) -> list[bytes]:
        """Store the prompt's blocks (full blocks plus one partial tail)
        from its per-position K/V, sharing any blocks already cached.
        Extending a cached partial block that other sequences still
        reference allocates a NEW block (copy-on-write) — blocks are
        immutable once published. Returns held block refs."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        with self._mu:
            self._clock += 1
            parent = ROOT
            held: list[bytes] = []
            pos = 0
            while pos < ids.size:
                take = min(self.block_size, ids.size - pos)
                chunk = ids[pos:pos + take]
                d = _digest(parent, chunk)
                blk = self._table.get(d)
                if blk is None:
                    prev = self._table.get(parent)
                    if prev is not None and not prev.full:
                        # can't chain off a partial block — stop here
                        break
                    blk = _Block(
                        digest=d, parent=parent, ids=chunk.copy(),
                        kv={p: a[pos:pos + take].copy()
                            for p, a in kv.items()},
                        full=take == self.block_size,
                    )
                    if self._covered_by_sibling(blk):
                        # a longer partial with the same content prefix
                        # already exists — adding this one only splits
                        # future matches
                        break
                    if any(self._prefixed_partial(blk)):
                        # the new block EXTENDS a partial some sequence
                        # still references: publish beside it instead of
                        # mutating it — copy-on-write on divergence
                        self.metrics["cow_copies_total"] += 1
                    self._table[d] = blk
                    if parent != ROOT:
                        self._table[parent].children.add(d)
                    self.metrics["blocks_cached"] = len(self._table)
                self._ref(blk)
                held.append(d)
                if not blk.full:
                    break  # a partial tail ends the chain by definition
                parent = d
                pos += take
            self._evict_to_capacity()
            if held:
                log_event("kv", "pool", "publish",
                          digests=[d.hex() for d in held],
                          rcs=[self._table[d].refcount for d in held])
            return held

    def _prefixed_partial(self, blk: _Block):
        """Live partial siblings whose content is a strict prefix of
        `blk` — the blocks a naive in-place extension would corrupt."""
        sibs = (self._table[blk.parent].children if blk.parent != ROOT
                else self._root_children())
        for d in list(sibs):
            sib = self._table.get(d)
            if sib is not None and not sib.full and sib.refcount > 0 \
                    and sib.ids.size < blk.ids.size \
                    and np.array_equal(sib.ids, blk.ids[:sib.ids.size]):
                yield sib

    def _covered_by_sibling(self, blk: _Block) -> bool:
        """True when an existing partial sibling already stores `blk`'s
        exact content as its prefix (so matching uses the longer one)."""
        sibs = (self._table[blk.parent].children if blk.parent != ROOT
                else self._root_children())
        for d in sibs:
            sib = self._table.get(d)
            if sib is not None and not sib.full \
                    and sib.ids.size >= blk.ids.size \
                    and np.array_equal(sib.ids[:blk.ids.size], blk.ids):
                return True
        return False

    def extend(self, ref: bytes, ids, kv: dict[str, np.ndarray]) -> bytes:
        """Grow a held partial block with more positions. Shared blocks
        (refcount > 1) are copied first — copy-on-write on divergence —
        so the other holders keep matching the block they admitted
        against. Returns the (possibly new) held ref."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        with self._mu:
            self._clock += 1
            blk = self._table.get(ref)
            if blk is None:
                raise KeyError("unknown block ref")
            if blk.full:
                raise ValueError("cannot extend a full block")
            if blk.ids.size + ids.size > self.block_size:
                raise ValueError(
                    f"extension {ids.size} overflows block "
                    f"(have {blk.ids.size}, block_size {self.block_size})")
            new_ids = np.concatenate([blk.ids, ids])
            d = _digest(blk.parent, new_ids)
            existing = self._table.get(d)
            if existing is not None:
                # the identical extension is already published (two rows
                # decoding the same continuation off a shared tail):
                # SHARE it — overwriting would orphan its holders'
                # refcounts and a later sole-holder extend would drop a
                # block someone still references
                self._ref(existing)
                if blk.refcount > 1:
                    self._unref(blk)
                else:
                    self._drop(blk)
                self.metrics["blocks_cached"] = len(self._table)
                log_event("kv", "pool", "extend", parent=ref.hex(),
                          digest=d.hex(), cow=False,
                          rc=existing.refcount)
                return d
            new = _Block(
                digest=d, parent=blk.parent, ids=new_ids,
                kv={p: np.concatenate([blk.kv[p], kv[p]], axis=0)
                    for p in blk.kv},
                full=new_ids.size == self.block_size,
            )
            cow = blk.refcount > 1
            if cow:
                # shared: publish the extension beside the original
                self.metrics["cow_copies_total"] += 1
                self._unref(blk)
            else:
                # sole holder: the original entry retires with us
                self._drop(blk)
            self._table[d] = new
            self._ref(new)
            if blk.parent != ROOT:
                self._table[blk.parent].children.add(d)
            self.metrics["blocks_cached"] = len(self._table)
            self._evict_to_capacity()
            log_event("kv", "pool", "extend", parent=ref.hex(),
                      digest=d.hex(), cow=cow, rc=new.refcount)
            return d

    def append_child(self, parent: bytes, ids,
                     kv: dict[str, np.ndarray]) -> bytes:
        """Publish ONE new block (partial or full) as a child of `parent`
        (a held FULL block, or ROOT) and acquire a reference on it — the
        decode-growth allocation path (SequenceChain.append calls this at
        every block boundary). An identical block already cached is
        shared instead of duplicated (two greedy decodes of the same
        prompt converge onto one chain); publishing beside a live partial
        whose content this block extends counts a COW copy exactly like
        insert()'s divergence path."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        if not 0 < ids.size <= self.block_size:
            raise ValueError(
                f"block of {ids.size} tokens (block_size "
                f"{self.block_size})")
        with self._mu:
            self._clock += 1
            if parent != ROOT:
                par = self._table.get(parent)
                if par is None:
                    raise KeyError("unknown parent block ref")
                if not par.full:
                    raise ValueError("cannot chain off a partial block")
            d = _digest(parent, ids)
            blk = self._table.get(d)
            if blk is None:
                blk = _Block(
                    digest=d, parent=parent, ids=ids.copy(),
                    kv={p: np.asarray(a)[:ids.size].copy()
                        for p, a in kv.items()},
                    full=ids.size == self.block_size,
                )
                if any(self._prefixed_partial(blk)):
                    self.metrics["cow_copies_total"] += 1
                self._table[d] = blk
                if parent != ROOT:
                    self._table[parent].children.add(d)
                self.metrics["blocks_cached"] = len(self._table)
            self._ref(blk)
            self._evict_to_capacity()
            log_event("kv", "pool", "publish", digests=[d.hex()],
                      rcs=[blk.refcount])
            return d

    # ------------------------------------------------- adoption / gather

    def adopt(self, refs: list[bytes]) -> None:
        """Acquire one additional reference per block of a chain BY
        DIGEST — the disaggregated handoff's contract: a prefill replica
        publishes its finished chain, and a decode replica (in another
        process, eventually) re-acquires it from the digests alone.
        Raises KeyError if any block is gone (the publisher must hold its
        own refs until the adopter confirms)."""
        with self._mu:
            self._clock += 1
            blocks = []
            for d in refs:
                blk = self._table.get(d)
                if blk is None:
                    raise KeyError("chain block evicted before adoption")
                blocks.append(blk)
            for blk in blocks:
                self._ref(blk)
                log_event("kv", "pool", "adopt", digest=blk.digest.hex(),
                          rc=blk.refcount)

    def gather(self, refs: list[bytes]):
        """Materialize a held chain: (token ids, per-leaf concatenated
        K/V) over every position the chain covers — what seeds a decode
        replica's row cache on adoption or resume. The caller must hold
        references on every block (adopt/insert/append_child)."""
        with self._mu:
            self._clock += 1
            blocks = []
            for d in refs:
                blk = self._table.get(d)
                if blk is None:
                    raise KeyError("unknown block ref")
                blk.last_used = self._clock
                blocks.append(blk)
        if not blocks:
            return np.zeros((0,), np.int32), {}
        # concatenate OUTSIDE the pool lock: blocks are immutable once
        # published and the snapshot above keeps them alive, while this
        # copy is the largest single memory op in the pool (a whole
        # request's K/V) — holding _mu here would stall every other
        # replica's admission/append hot path behind each handoff
        ids = np.concatenate([b.ids for b in blocks])
        kv = {p: np.concatenate([b.kv[p] for b in blocks], axis=0)
              for p in blocks[0].kv}
        return ids, kv

    def chain_info(self, refs: list[bytes]) -> tuple[int, int]:
        """(total positions, positions in the partial tail — 0 when the
        chain ends on a full block) for a held chain."""
        with self._mu:
            total = tail = 0
            for d in refs:
                blk = self._table[d]
                total += blk.ids.size
                tail = 0 if blk.full else blk.ids.size
            return total, tail

    # ----------------------------------------------------------- release

    def release(self, refs: list[bytes]) -> None:
        """Drop the references a retired sequence held; unreferenced
        blocks stay cached (that is the reuse) until LRU eviction."""
        with self._mu:
            dropped: list[_Block] = []
            for d in refs:
                blk = self._table.get(d)
                if blk is not None:
                    self._unref(blk)
                    dropped.append(blk)
            if dropped:
                log_event("kv", "pool", "release",
                          digests=[b.digest.hex() for b in dropped],
                          rcs=[b.refcount for b in dropped])
            self._evict_to_capacity()

    def _drop(self, blk: _Block) -> None:
        if blk.refcount > 0:
            # a still-held block leaving the table (extend's sole-holder
            # retire-with-us path) leaves the pinned set too
            self._pinned -= 1
        self._table.pop(blk.digest, None)
        parent = self._table.get(blk.parent)
        if parent is not None:
            parent.children.discard(blk.digest)

    def _evict_to_capacity(self) -> None:
        """LRU, leaf-first: only unreferenced childless blocks leave, so
        a live sequence's chain (and any chain it hangs off) survives."""
        while len(self._table) > self.capacity_blocks:
            victims = [b for b in self._table.values()
                       if b.refcount == 0 and not b.children]
            if not victims:
                return  # everything evictable is pinned — over-capacity
            victim = min(victims, key=lambda b: b.last_used)
            self._drop(victim)
            self.metrics["blocks_evicted_total"] += 1
        self.metrics["blocks_cached"] = len(self._table)

    # ------------------------------------------------------------- debug

    def refcounts(self) -> dict[bytes, int]:
        with self._mu:
            return {d: b.refcount for d, b in self._table.items()}

    def __len__(self) -> int:
        with self._mu:
            return len(self._table)


# ------------------------------------------------------- sequence chains


class SequenceChain:
    """A decode row's held block chain over its WHOLE lifetime (prompt +
    generated tokens) — the per-row block table the engine keeps while
    the row is in flight.

    Ownership travels with the object: the admitting engine builds it
    from insert()'s held refs, appends each decode dispatch's new K/V
    (allocate-on-boundary: the partial tail extends via the pool's
    COW-safe ``extend`` until full, then a fresh child block via
    ``append_child``), and releases it at retire. On a replica kill the
    engine transfers the chain to the request handle instead of
    releasing, and the router hands it to the surviving replica — whose
    resume admission seeds its row cache from ``pool.gather`` and keeps
    appending to the same object.

    ``frozen`` marks a chain that could not cover every cached position
    (insert stopped early at a covered-by-sibling or partial-parent
    boundary): it releases normally but never appends and never resumes
    — the requeue path falls back to re-decoding from scratch.
    """

    def __init__(self, pool: PagedKVPool, refs: list[bytes],
                 expect_length: int | None = None):
        self.pool = pool
        self.refs = list(refs)
        self.length, self._tail_len = pool.chain_info(self.refs)
        self.frozen = (expect_length is not None
                       and self.length != expect_length)

    def append(self, ids, kv: dict[str, np.ndarray]) -> None:
        """Append `len(ids)` generated positions' K/V to the chain —
        `kv` maps leaf path -> (n, kv_heads, head_dim). Fills the partial
        tail first (pool.extend: COW when another sequence shares it),
        then allocates fresh blocks at each boundary."""
        if self.frozen:
            raise ValueError("cannot append to a frozen chain")
        ids = np.asarray(ids, np.int32).reshape(-1)
        bs = self.pool.block_size
        part = {p: np.asarray(a) for p, a in kv.items()}
        i = 0
        while i < ids.size:
            if self._tail_len:
                take = min(bs - self._tail_len, ids.size - i)
                self.refs[-1] = self.pool.extend(
                    self.refs[-1], ids[i:i + take],
                    {p: a[i:i + take] for p, a in part.items()})
                self._tail_len += take
            else:
                take = min(bs, ids.size - i)
                parent = self.refs[-1] if self.refs else ROOT
                self.refs.append(self.pool.append_child(
                    parent, ids[i:i + take],
                    {p: a[i:i + take] for p, a in part.items()}))
                self._tail_len = take
            if self._tail_len == bs:
                self._tail_len = 0
            i += take
            self.length += take

    def release(self) -> None:
        self.pool.release(self.refs)
        self.refs = []
        self.length = 0
        self._tail_len = 0

