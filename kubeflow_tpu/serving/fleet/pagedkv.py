"""Paged/block KV cache with prefix reuse — the fleet's prefill saver.

Decode is HBM-bandwidth-bound, but PREFILL is compute-bound and scales
with prompt length — and production prompts share long prefixes (the
system prompt, few-shot preambles). vLLM pages the decode cache; this
tier pages the *prefix* store instead, because the in-engine decode cache
is already a fixed-row static-shape buffer (the TPU-idiomatic layout,
serving/continuous.py) and what repeats across requests is the prompt:

  - prompts split into fixed-size BLOCKS (`block_size` tokens); each
    fully-prefilled block's K/V (every layer, rope-rotated, position
    [p0, p1)) is stored once, keyed by the CHAIN HASH of its content —
    sha1(parent_digest + token bytes) — so block identity encodes the
    whole prefix, not just the block's own tokens;
  - the block table is REFCOUNTED: a sequence holds references to the
    blocks its prompt maps to from admission to retire, and eviction
    (LRU, leaf-first) only ever removes unreferenced blocks;
  - divergence is COPY-ON-WRITE: blocks are immutable — two prompts that
    split mid-block simply stop matching at the split, and extending a
    shared partial tail block allocates a new block (``cow_copies``)
    instead of mutating the one the other sequence still references.

On admission the engine asks ``match(ids)``: the longest cached chain
comes back as gathered per-layer K/V, is written into the row cache at
positions [0, shared) with ``cache_index``/``pos_index`` seeded to
`shared`, and the model prefills ONLY the suffix — the per-row index
machinery models/gpt.py keeps for continuous batching makes the seeded
row indistinguishable from one the model prefilled itself. Position
alignment makes the reuse exact: cached K carries its absolute-position
rotation, and a prompt prefix always sits at positions [0, L).

Host-side numpy on purpose: the pool is the fleet tier's shared store
(N engines on N threads hit one pool under one lock), and the arrays
only cross to the device inside the admitting engine's jitted prefill.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from kubeflow_tpu.analysis.lockcheck import make_lock

#: digest of the empty prefix — the chain root every block hangs off
ROOT = b"kftpu-fleet-root"


def _digest(parent: bytes, ids: np.ndarray) -> bytes:
    return hashlib.sha1(parent + ids.astype(np.int32).tobytes()).digest()


# --------------------------------------------------- cache-pytree helpers


def _walk(tree, prefix=""):
    """Yield (path, leaf) for every array leaf of a nested-dict cache
    pytree — the flax cache collection is plain dicts, so a stable
    '/'-joined key path is enough to pair extract with seed."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _walk(tree[k], f"{prefix}/{k}")
    else:
        yield prefix, tree


def extract_prompt_kv(row_cache, length: int) -> dict[str, np.ndarray]:
    """Per-position K/V of a batch-1 row cache's first `length` positions:
    {leaf path -> (length, kv_heads, head_dim) np array} for every
    cached_key/cached_value leaf. The pool stores slices of these."""
    out: dict[str, np.ndarray] = {}
    for path, leaf in _walk(row_cache):
        name = path.rsplit("/", 1)[-1]
        if name in ("cached_key", "cached_value"):
            out[path] = np.asarray(leaf)[0, :length].copy()
    return out


def make_row_template(live_cache) -> dict:
    """Batch-1 zeroed np twin of the engine's live cache pytree — the
    starting point for a seeded (prefix-reused) or chunked prefill."""

    def zero(tree):
        if isinstance(tree, dict):
            return {k: zero(v) for k, v in tree.items()}
        a = np.asarray(tree)
        return np.zeros((1,) + a.shape[1:], a.dtype)

    return zero(live_cache)


def seed_row_cache(template: dict, kv: dict[str, np.ndarray],
                   shared: int) -> dict:
    """Fresh batch-1 row cache with the pool's gathered K/V written at
    positions [0, shared) and every cache_index/pos_index leaf set to
    `shared` — exactly the state a one-shot prefill of those tokens
    leaves behind, so the suffix prefill continues seamlessly."""

    def build(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: build(v, f"{prefix}/{k}") for k, v in tree.items()}
        name = prefix.rsplit("/", 1)[-1]
        if name in ("cache_index", "pos_index"):
            return np.full_like(tree, shared)
        got = kv.get(prefix)
        if got is None:
            return tree.copy()
        buf = tree.copy()
        buf[0, :shared] = got[:shared]
        return buf

    return build(template)


# ------------------------------------------------------------------ pool


@dataclass
class _Block:
    digest: bytes
    parent: bytes
    ids: np.ndarray                      # (n,) int32, n <= block_size
    kv: dict[str, np.ndarray]            # path -> (n, kvh, d)
    full: bool
    refcount: int = 0
    last_used: int = 0
    children: set = field(default_factory=set)


@dataclass
class PrefixMatch:
    """Result of PagedKVPool.match: `length` cached positions, gathered
    K/V per leaf path, and the block refs the caller now holds (release
    via PagedKVPool.release when the sequence retires)."""

    length: int
    kv: dict[str, np.ndarray]
    blocks: list[bytes]


class PagedKVPool:
    """Refcounted block table over prompt-prefix K/V (module docstring)."""

    def __init__(self, block_size: int = 8, capacity_blocks: int = 1024):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if capacity_blocks < 1:
            raise ValueError(
                f"capacity_blocks must be >= 1, got {capacity_blocks}")
        self.block_size = int(block_size)
        self.capacity_blocks = int(capacity_blocks)
        self._table: dict[bytes, _Block] = {}
        self._clock = 0
        self._mu = make_lock("fleet.PagedKVPool._mu")
        self.metrics = {
            "blocks_cached": 0,
            "blocks_evicted_total": 0,
            "blocks_reused_total": 0,
            "tokens_reused_total": 0,
            "cow_copies_total": 0,
        }

    # ------------------------------------------------------------- match

    def match(self, ids) -> PrefixMatch:
        """Longest cached prefix of `ids`: full-block chain first, then at
        most one partial tail block whose content is a prefix of the
        remainder. Acquires one reference per matched block."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        with self._mu:
            self._clock += 1
            parent = ROOT
            blocks: list[_Block] = []
            pos = 0
            while pos + self.block_size <= ids.size:
                d = _digest(parent, ids[pos:pos + self.block_size])
                blk = self._table.get(d)
                if blk is None or not blk.full:
                    break
                blocks.append(blk)
                parent = d
                pos += self.block_size
            # partial tail: the longest child of the last matched block
            # whose tokens prefix the remaining ids (COW keeps several
            # divergent partials alive side by side — pick the best)
            tail: _Block | None = None
            rest = ids[pos:]
            siblings = (self._root_children() if parent == ROOT
                        else self._table[parent].children)
            for child_d in list(siblings):
                child = self._table.get(child_d)
                if child is None or child.full or child.ids.size > rest.size:
                    continue
                if np.array_equal(child.ids, rest[:child.ids.size]) and (
                        tail is None or child.ids.size > tail.ids.size):
                    tail = child
            if tail is not None:
                blocks.append(tail)
                pos += tail.ids.size
            for blk in blocks:
                blk.refcount += 1
                blk.last_used = self._clock
            kv: dict[str, np.ndarray] = {}
            if blocks:
                for path in blocks[0].kv:
                    kv[path] = np.concatenate(
                        [b.kv[path] for b in blocks], axis=0)
                self.metrics["blocks_reused_total"] += len(blocks)
                self.metrics["tokens_reused_total"] += pos
            return PrefixMatch(length=pos, kv=kv,
                               blocks=[b.digest for b in blocks])

    def _root_children(self):
        return [d for d, b in self._table.items() if b.parent == ROOT]

    # ------------------------------------------------------------ insert

    def insert(self, ids, kv: dict[str, np.ndarray]) -> list[bytes]:
        """Store the prompt's blocks (full blocks plus one partial tail)
        from its per-position K/V, sharing any blocks already cached.
        Extending a cached partial block that other sequences still
        reference allocates a NEW block (copy-on-write) — blocks are
        immutable once published. Returns held block refs."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        with self._mu:
            self._clock += 1
            parent = ROOT
            held: list[bytes] = []
            pos = 0
            while pos < ids.size:
                take = min(self.block_size, ids.size - pos)
                chunk = ids[pos:pos + take]
                d = _digest(parent, chunk)
                blk = self._table.get(d)
                if blk is None:
                    prev = self._table.get(parent)
                    if prev is not None and not prev.full:
                        # can't chain off a partial block — stop here
                        break
                    blk = _Block(
                        digest=d, parent=parent, ids=chunk.copy(),
                        kv={p: a[pos:pos + take].copy()
                            for p, a in kv.items()},
                        full=take == self.block_size,
                    )
                    if self._covered_by_sibling(blk):
                        # a longer partial with the same content prefix
                        # already exists — adding this one only splits
                        # future matches
                        break
                    if any(self._prefixed_partial(blk)):
                        # the new block EXTENDS a partial some sequence
                        # still references: publish beside it instead of
                        # mutating it — copy-on-write on divergence
                        self.metrics["cow_copies_total"] += 1
                    self._table[d] = blk
                    if parent != ROOT:
                        self._table[parent].children.add(d)
                    self.metrics["blocks_cached"] = len(self._table)
                blk.refcount += 1
                blk.last_used = self._clock
                held.append(d)
                if not blk.full:
                    break  # a partial tail ends the chain by definition
                parent = d
                pos += take
            self._evict_to_capacity()
            return held

    def _prefixed_partial(self, blk: _Block):
        """Live partial siblings whose content is a strict prefix of
        `blk` — the blocks a naive in-place extension would corrupt."""
        sibs = (self._table[blk.parent].children if blk.parent != ROOT
                else self._root_children())
        for d in list(sibs):
            sib = self._table.get(d)
            if sib is not None and not sib.full and sib.refcount > 0 \
                    and sib.ids.size < blk.ids.size \
                    and np.array_equal(sib.ids, blk.ids[:sib.ids.size]):
                yield sib

    def _covered_by_sibling(self, blk: _Block) -> bool:
        """True when an existing partial sibling already stores `blk`'s
        exact content as its prefix (so matching uses the longer one)."""
        sibs = (self._table[blk.parent].children if blk.parent != ROOT
                else self._root_children())
        for d in sibs:
            sib = self._table.get(d)
            if sib is not None and not sib.full \
                    and sib.ids.size >= blk.ids.size \
                    and np.array_equal(sib.ids[:blk.ids.size], blk.ids):
                return True
        return False

    def extend(self, ref: bytes, ids, kv: dict[str, np.ndarray]) -> bytes:
        """Grow a held partial block with more positions. Shared blocks
        (refcount > 1) are copied first — copy-on-write on divergence —
        so the other holders keep matching the block they admitted
        against. Returns the (possibly new) held ref."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        with self._mu:
            self._clock += 1
            blk = self._table.get(ref)
            if blk is None:
                raise KeyError("unknown block ref")
            if blk.full:
                raise ValueError("cannot extend a full block")
            if blk.ids.size + ids.size > self.block_size:
                raise ValueError(
                    f"extension {ids.size} overflows block "
                    f"(have {blk.ids.size}, block_size {self.block_size})")
            new_ids = np.concatenate([blk.ids, ids])
            d = _digest(blk.parent, new_ids)
            new = _Block(
                digest=d, parent=blk.parent, ids=new_ids,
                kv={p: np.concatenate([blk.kv[p], kv[p]], axis=0)
                    for p in blk.kv},
                full=new_ids.size == self.block_size,
                refcount=1, last_used=self._clock,
            )
            if blk.refcount > 1:
                # shared: publish the extension beside the original
                self.metrics["cow_copies_total"] += 1
                blk.refcount -= 1
            else:
                # sole holder: the original entry retires with us
                self._drop(blk)
            self._table[d] = new
            if blk.parent != ROOT:
                self._table[blk.parent].children.add(d)
            self.metrics["blocks_cached"] = len(self._table)
            self._evict_to_capacity()
            return d

    # ----------------------------------------------------------- release

    def release(self, refs: list[bytes]) -> None:
        """Drop the references a retired sequence held; unreferenced
        blocks stay cached (that is the reuse) until LRU eviction."""
        with self._mu:
            for d in refs:
                blk = self._table.get(d)
                if blk is not None and blk.refcount > 0:
                    blk.refcount -= 1
            self._evict_to_capacity()

    def _drop(self, blk: _Block) -> None:
        self._table.pop(blk.digest, None)
        parent = self._table.get(blk.parent)
        if parent is not None:
            parent.children.discard(blk.digest)

    def _evict_to_capacity(self) -> None:
        """LRU, leaf-first: only unreferenced childless blocks leave, so
        a live sequence's chain (and any chain it hangs off) survives."""
        while len(self._table) > self.capacity_blocks:
            victims = [b for b in self._table.values()
                       if b.refcount == 0 and not b.children]
            if not victims:
                return  # everything evictable is pinned — over-capacity
            victim = min(victims, key=lambda b: b.last_used)
            self._drop(victim)
            self.metrics["blocks_evicted_total"] += 1
        self.metrics["blocks_cached"] = len(self._table)

    # ------------------------------------------------------------- debug

    def refcounts(self) -> dict[bytes, int]:
        with self._mu:
            return {d: b.refcount for d, b in self._table.items()}

    def __len__(self) -> int:
        with self._mu:
            return len(self._table)

