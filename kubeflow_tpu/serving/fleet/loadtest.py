"""Seeded fleet load-test harness — the serving analogue of the chaos
drills.

A recovery claim that only production traffic can falsify is
unfalsifiable; chaos.py solved that for training with seeded fault
plans, and this harness does the same for serving: OPEN-LOOP seeded
arrivals (the arrival process does not slow down because the fleet did —
the production failure mode closed-loop benchmarks hide), per-request
TTFT and tokens/sec accounting from the engine's own token timestamps,
and an optional mid-run replica kill whose acceptance bar is ZERO
dropped requests (the router requeues everything the dead replica
carried).

Two drive modes share one report shape:

  - ``run_loadtest`` (threaded): replicas tick on their serving threads,
    arrivals sleep out a seeded exponential schedule in wall seconds,
    the kill fires from a timer — the integration drill
    (tests/test_fleet.py).
  - ``run_loadtest_sync`` (tick-driven): no threads, no sleeps — one
    round-robin tick across live replicas per step, arrivals and the
    kill scheduled in TICK units. Everything the run does is engine
    work, so TTFT expressed in anchor units is machine-speed invariant —
    this is the cpu-proxy ``serve_fleet`` gate's mode
    (profiling/cpu_proxy.py).

Requests may carry a shared prefix (`shared_prefix` tokens prepended to
every prompt) to exercise paged-KV prefix reuse under load.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from kubeflow_tpu.serving.fleet.router import FleetOverloaded, FleetRouter


@dataclass
class LoadReport:
    """What a load run proved: completion accounting (dropped MUST be 0
    under a replica kill — the requeue contract), TTFT/token-rate
    percentiles, and the prefill-unit ledger backing prefix-reuse
    claims."""

    n_requests: int
    completed: int = 0
    shed: int = 0
    dropped: int = 0
    requeued: int = 0
    # the resume-from-KV rescue split: requeues that resumed from the
    # dead replica's surviving block chain vs. re-decoded from scratch
    # (requeued - resumed), plus the tokens those resumes salvaged
    resumed: int = 0
    resumed_tokens: int = 0
    ttft_s: list = field(default_factory=list)
    tokens_per_s: list = field(default_factory=list)
    # per-request shed-retry attribution (threaded mode): how many
    # submit attempts each request took and how long it spent in
    # CLIENT-side Retry-After backoff — kept apart from TTFT so the
    # harness percentiles separate server-side queueing from the
    # client's own waiting (previously conflated into wall time)
    attempts: list = field(default_factory=list)
    retry_wait_s: list = field(default_factory=list)
    wall_s: float = 0.0
    ticks: int = 0  # sync mode: round-robin loop passes driven
    tokens_out: int = 0
    prefill_tokens_total: int = 0
    prefill_tokens_reused: int = 0

    @staticmethod
    def _pct(samples: list, q: float) -> float:
        if not samples:
            return 0.0
        s = sorted(samples)
        return s[min(len(s) - 1, int(len(s) * q))]

    def summary(self) -> dict:
        return {
            "n_requests": self.n_requests,
            "completed": self.completed,
            "shed": self.shed,
            "dropped": self.dropped,
            "requeued": self.requeued,
            "resumed": self.resumed,
            "resumed_tokens": self.resumed_tokens,
            "wall_s": round(self.wall_s, 6),
            "tokens_out": self.tokens_out,
            "tokens_per_s_total": (
                round(self.tokens_out / self.wall_s, 3)
                if self.wall_s > 0 else 0.0),
            "ttft_p50_s": round(self._pct(self.ttft_s, 0.50), 6),
            "ttft_p99_s": round(self._pct(self.ttft_s, 0.99), 6),
            "row_tokens_per_s_p50": round(
                self._pct(self.tokens_per_s, 0.50), 3),
            "prefill_tokens_total": self.prefill_tokens_total,
            "prefill_tokens_reused": self.prefill_tokens_reused,
            "retried": sum(1 for a in self.attempts if a > 1),
            "attempts_mean": round(
                sum(self.attempts) / len(self.attempts), 3)
            if self.attempts else 0.0,
            "retry_wait_p50_s": round(
                self._pct(self.retry_wait_s, 0.50), 6),
            "retry_wait_p99_s": round(
                self._pct(self.retry_wait_s, 0.99), 6),
        }


def make_prompts(n: int, seed: int, vocab: int, prompt_len,
                 shared_prefix: int = 0) -> list[np.ndarray]:
    """Seeded request prompts; `prompt_len` is an int or (lo, hi). The
    first `shared_prefix` tokens are IDENTICAL across requests (the
    system-prompt shape paged KV exists for)."""
    rng = random.Random(seed)
    lo, hi = ((prompt_len, prompt_len) if isinstance(prompt_len, int)
              else prompt_len)
    prefix = np.asarray([rng.randrange(1, vocab)
                         for _ in range(shared_prefix)], np.int32)
    out = []
    for _ in range(n):
        n_tok = rng.randint(lo, hi)
        body = np.asarray([rng.randrange(1, vocab) for _ in range(n_tok)],
                          np.int32)
        out.append(np.concatenate([prefix, body]) if shared_prefix
                   else body)
    return out


def _counters(router: FleetRouter) -> dict:
    """Snapshot of the cumulative counters a run reports as DELTAS, so a
    reused router/pool (warmup traffic, back-to-back runs) can never
    inflate a report — LoadReport states what THIS run proved."""
    return {
        "requeued": router.metrics["requests_requeued_total"],
        "resumed": router.metrics["requeues_resumed_total"],
        "resumed_tokens": router.metrics["requeue_resumed_tokens_total"],
        "prefill_total": sum(r.engine.prefill_tokens_total
                             for r in router.replicas),
        "prefill_reused": sum(r.engine.prefill_tokens_reused
                              for r in router.replicas),
    }


def _collect(router: FleetRouter, report: LoadReport, handles: list,
             base: dict) -> LoadReport:
    for h in handles:
        if h is None:
            continue
        if h.error is not None or not h.done.is_set():
            report.dropped += 1
            continue
        report.completed += 1
        report.tokens_out += len(h.tokens)
        if h.ttft_s is not None:
            report.ttft_s.append(h.ttft_s)
        if h.tokens_per_s is not None:
            report.tokens_per_s.append(h.tokens_per_s)
    now = _counters(router)
    report.requeued = now["requeued"] - base["requeued"]
    report.resumed = now["resumed"] - base["resumed"]
    report.resumed_tokens = now["resumed_tokens"] - base["resumed_tokens"]
    report.prefill_tokens_total = now["prefill_total"] \
        - base["prefill_total"]
    report.prefill_tokens_reused = now["prefill_reused"] \
        - base["prefill_reused"]
    return report


def run_loadtest(router: FleetRouter, prompts: list[np.ndarray],
                 seed: int = 0, mean_gap_s: float = 0.005,
                 new_tokens: int = 8, kill_after: int = 0,
                 kill_replica=None, timeout_s: float = 120.0,
                 shed_retries: int = 2) -> LoadReport:
    """Threaded open-loop run: seeded exponential inter-arrival gaps,
    optional replica kill once `kill_after` requests have been submitted
    (0 = before the first, mirroring run_loadtest_sync's kill_at_tick).
    Shed requests re-dial after the router's Retry-After hint up to
    `shed_retries` times (the serving/client.py contract) — a shed that
    exhausts its retries counts `shed`, never silently vanishes."""
    rng = random.Random(seed)
    gaps = [rng.expovariate(1.0 / mean_gap_s) if mean_gap_s > 0 else 0.0
            for _ in prompts]
    report = LoadReport(n_requests=len(prompts))
    handles: list = [None] * len(prompts)
    base = _counters(router)
    pacer = threading.Event()  # deadline-style waits, not naked sleeps
    router.start()
    t0 = time.perf_counter()
    try:
        for i, (p, gap) in enumerate(zip(prompts, gaps)):
            pacer.wait(gap)
            if kill_replica is not None and i == kill_after:
                router.kill_replica(kill_replica)
            waited = 0.0
            for attempt in range(shed_retries + 1):
                try:
                    handles[i] = router.submit(p, max_new_tokens=new_tokens)
                    break
                except FleetOverloaded as exc:
                    if attempt == shed_retries:
                        report.shed += 1
                    else:
                        hinted = min(exc.retry_after_s, 2.0)
                        pacer.wait(hinted)
                        waited += hinted
            # recorded for EVERY request (retries or not) so the
            # percentiles line up index-free with ttft_s
            report.attempts.append(attempt + 1)
            report.retry_wait_s.append(waited)
        deadline = time.monotonic() + timeout_s
        for h in handles:
            if h is not None:
                h.done.wait(max(0.0, deadline - time.monotonic()))
    finally:
        report.wall_s = time.perf_counter() - t0
        router.stop()
    return _collect(router, report, handles, base)


def run_loadtest_sync(router: FleetRouter, prompts: list[np.ndarray],
                      seed: int = 0, mean_gap_ticks: float = 1.0,
                      new_tokens: int = 8, kill_at_tick: int = 0,
                      kill_replica=None, max_ticks: int = 100000,
                      on_tick=None) -> LoadReport:
    """Tick-driven run (no threads, no sleeps): arrivals land on seeded
    tick offsets, the kill fires at `kill_at_tick`, and every unit of
    work is an engine tick — machine-speed cancels out of anchor-relative
    ratios (the cpu-proxy serve_fleet mode). `on_tick(tick, router)`,
    when given, runs after each round-robin pass — the monitoring
    plane's sampling hook (the serve_fleet drill records the fleet's
    counter families into its TSDB here)."""
    rng = random.Random(seed)
    arrivals: list[tuple[int, int]] = []  # (tick, prompt index)
    t = 0.0
    for i in range(len(prompts)):
        t += rng.expovariate(1.0 / mean_gap_ticks) if mean_gap_ticks > 0 \
            else 0.0
        arrivals.append((int(t), i))
    report = LoadReport(n_requests=len(prompts))
    handles: list = [None] * len(prompts)
    base = _counters(router)
    killed = kill_replica is None
    t0 = time.perf_counter()
    tick = 0
    while tick < max_ticks:
        if not killed and tick >= kill_at_tick:
            router.kill_replica(kill_replica)
            killed = True
        while arrivals and arrivals[0][0] <= tick:
            _, i = arrivals.pop(0)
            try:
                handles[i] = router.submit(
                    prompts[i], max_new_tokens=new_tokens)
            except FleetOverloaded:
                report.shed += 1
        busy = False
        for rep in router.replicas:
            if rep.alive:
                busy = rep.engine.tick() or busy
        if on_tick is not None:
            on_tick(tick, router)
        tick += 1
        if not busy and not arrivals and killed:
            break
    report.wall_s = time.perf_counter() - t0
    report.ticks = tick
    return _collect(router, report, handles, base)
