"""Pod wire protocol — framing, envelopes, and the KV-chain handoff codec.

The pod tier (podworker.py / podclient.py) speaks length-prefixed JSON
over a local AF_UNIX socket: a 4-byte big-endian length followed by one
UTF-8 JSON object per frame (NDJSON semantics — one object per message —
with an explicit length prefix so a torn TCP-style partial read is
DETECTABLE instead of silently resynchronized). This module is the
transport's pure half: no sockets are owned here, no jax is imported —
the router can import the exception types without dragging a worker
runtime into its process.

Envelope contract (client -> worker):

    {"verb": str, "seq": int, "deadline_s": float|null, ...payload}

`deadline_s` is the REMAINING budget at send time (a wall-clock instant
would not survive clock skew between processes; a remaining-seconds
relative deadline is what gRPC propagates for the same reason). The
worker re-anchors it on receipt and rejects already-expired work with a
504-shaped error reply instead of burning ticks on an answer nobody is
waiting for.

Reply contract (worker -> client):

    {"seq": int, "ok": true,  ...result}
    {"seq": int, "ok": false, "code": int, "error": str,
     "retry_after_s": float?}        # 503 carries Retry-After

Chain handoff codec: a finished prefill chain crosses the process
boundary as its token ids + per-leaf K/V (base64 of the raw array
bytes) + the pool's OWN content digests for every block + a sha256 over
the arrays. Deserialization re-inserts into the receiving pool and then
cross-checks the refs the local insert produced against the refs the
sender claimed — the chain digests are content-derived
(sha1(parent + ids)), so any corruption of ids in flight shows up as a
digest mismatch even before the sha256 of the K/V bytes is consulted.
This is the PR-3 checkpoint-manifest discipline applied to the KV path.
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
from typing import Any

import numpy as np

#: frame length prefix: 4-byte big-endian unsigned
_LEN = struct.Struct(">I")

#: hard per-frame ceiling — a corrupt length prefix must not convince the
#: reader to allocate gigabytes (chains of the test/proxy models are KB-MB)
MAX_FRAME_BYTES = 256 * 1024 * 1024


class PodWireError(RuntimeError):
    """A wire-level failure talking to a pod: connection reset, torn
    frame, truncated read. Retryable by policy — the client redials and
    retries; exhaustion escalates to pod death."""


class PodDead(RuntimeError):
    """The pod is gone (process exited, marked dead, or retries
    exhausted). Deliberately NOT a PodWireError: the client's
    retry_on=(PodWireError,) must never spin against a corpse — the
    router re-picks a replica instead."""


class PodDeadlineExpired(RuntimeError):
    """The propagated deadline was already spent when the worker saw
    the envelope (a 504-shaped reply). Not retryable: the budget is
    gone no matter how healthy the wire is."""


class PodCallError(RuntimeError):
    """An application-level refusal from the worker (bad verb, poisoned
    engine, resume-chain refusal). Carries the reply's `code`; not
    retryable at the transport layer."""

    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = int(code)


# ---------------------------------------------------- the wire registry
#
# Every verb name, error code, and envelope/reply/event field name the
# two endpoints exchange, spelled out ONCE. podclient.py and podworker.py
# must import these instead of inlining the strings: the rid-collision
# class of bug — client writes one spelling, worker reads another, and
# the reader just sees "unset" — is the envvars.py story replayed on the
# wire, so it gets the same cure (a single registry) and the same lint
# teeth (KFTPU-VERB flags literal drift in the endpoint modules).

# verbs (envelope F_VERB values; the worker dispatches _verb_<name>)
VERB_HELLO = "hello"
VERB_SUBMIT = "submit"
VERB_TICK = "tick"
VERB_DRAIN = "drain"
VERB_HEARTBEAT = "heartbeat"
VERB_KILL = "kill"
WIRE_VERBS = frozenset({
    VERB_HELLO, VERB_SUBMIT, VERB_TICK, VERB_DRAIN, VERB_HEARTBEAT,
    VERB_KILL,
})

# error-reply codes (HTTP-shaped, carried in F_CODE)
CODE_BAD_REQUEST = 400   # unknown verb / malformed envelope
CODE_CONFLICT = 409      # resume chain frozen on re-insert
CODE_FENCED = 410        # stale epoch — terminal for that claimant
CODE_INTERNAL = 500      # worker-side exception / dying engine
CODE_BUSY = 503          # queue full; carries F_RETRY_AFTER_S
CODE_DEADLINE = 504      # propagated deadline already spent
WIRE_CODES = frozenset({
    CODE_BAD_REQUEST, CODE_CONFLICT, CODE_FENCED, CODE_INTERNAL,
    CODE_BUSY, CODE_DEADLINE,
})

# envelope fields (client -> worker)
F_VERB = "verb"
F_SEQ = "seq"
F_EPOCH = "epoch"
F_DEADLINE_S = "deadline_s"
F_ACK = "ack"
F_N = "n"
F_RID = "rid"
F_PROMPT = "prompt"
F_MAX_NEW_TOKENS = "max_new_tokens"
F_EOS = "eos"
F_TEMPERATURE = "temperature"
F_KEEP_CHAIN = "keep_chain"
F_RESUME = "resume"

# reply fields (worker -> client)
F_OK = "ok"
F_CODE = "code"
F_ERROR = "error"
F_RETRY_AFTER_S = "retry_after_s"
F_EVENTS = "events"
F_BUSY = "busy"
F_DEPTH = "depth"
F_DUP = "dup"
F_DYING = "dying"
F_PORT = "port"
F_STEP_COUNT = "step_count"
F_TICK_ERROR = "tick_error"

# outbox event fields and kinds (inside F_EVENTS / F_CHAIN payloads)
F_EV = "ev"
F_ID = "id"
F_TOK = "tok"
F_TOKENS = "tokens"
F_RESUMED = "resumed"
F_CHAIN = "chain"
EV_TOKEN = "token"
EV_DONE = "done"
WIRE_EVENT_KINDS = frozenset({EV_TOKEN, EV_DONE})

WIRE_FIELDS = frozenset({
    F_VERB, F_SEQ, F_EPOCH, F_DEADLINE_S, F_ACK, F_N, F_RID, F_PROMPT,
    F_MAX_NEW_TOKENS, F_EOS, F_TEMPERATURE, F_KEEP_CHAIN, F_RESUME,
    F_OK, F_CODE, F_ERROR, F_RETRY_AFTER_S, F_EVENTS, F_BUSY, F_DEPTH,
    F_DUP, F_DYING, F_PORT, F_STEP_COUNT, F_TICK_ERROR,
    F_EV, F_ID, F_TOK, F_TOKENS, F_RESUMED, F_CHAIN,
})


# ------------------------------------------------------------- framing


def send_frame(sock: socket.socket, obj: dict) -> int:
    """Serialize `obj` and write one length-prefixed frame; returns the
    frame size in bytes (header included). Raises OSError on a dead
    socket — callers wrap transport faults into PodWireError."""
    data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)
    return _LEN.size + len(data)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly `n` bytes or raise PodWireError: a short read IS the
    torn-frame condition the length prefix exists to expose."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise PodWireError(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> dict:
    """Read one length-prefixed JSON frame. PodWireError on EOF, torn
    frame, oversized length, or undecodable payload."""
    (n,) = _LEN.unpack(recv_exact(sock, _LEN.size))
    if n > MAX_FRAME_BYTES:
        raise PodWireError(f"frame length {n} exceeds {MAX_FRAME_BYTES}")
    data = recv_exact(sock, n)
    try:
        obj = json.loads(data.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise PodWireError(f"undecodable frame: {e}") from e
    if not isinstance(obj, dict):
        raise PodWireError("frame payload is not an object")
    return obj


# ----------------------------------------------------------- transport


class Transport:
    """The dial-side wire seam: one object owning one stream socket,
    speaking the length-prefixed JSON framing above. The pod client
    holds a Transport instead of a raw socket so AF_UNIX (single-host,
    PR-15) and TCP (multi-host) are the SAME code path — connect /
    send_frame / recv_frame / close is the whole contract, and every
    fault surfaces as OSError (dial/send) or PodWireError (recv), which
    the client's retry supervisor already knows how to absorb.

    `sock` stays a public attribute on purpose: the chaos engine's
    torn-frame injection reads a deliberate partial frame straight off
    the socket, and tests reach in to sever a connection out from under
    the client (the ECONNRESET drill)."""

    #: wire kind tag ("unix" | "tcp") — carried into hellos and logs
    kind = "base"
    _family = -1

    def __init__(self, address):
        self.address = address
        self.sock: socket.socket | None = None

    def connect(self, timeout_s: float | None = None) -> "Transport":
        """Dial `address`; OSError propagates (the client's startup
        poll and redial supervisor own the retry decision)."""
        s = socket.socket(self._family, socket.SOCK_STREAM)
        if timeout_s is not None:
            s.settimeout(timeout_s)
        try:
            s.connect(self.address)
        except OSError:
            s.close()
            raise
        self.sock = s
        return self

    @property
    def connected(self) -> bool:
        return self.sock is not None

    def settimeout(self, timeout_s: float | None) -> None:
        if self.sock is not None:
            self.sock.settimeout(timeout_s)

    def send_frame(self, obj: dict) -> int:
        if self.sock is None:
            raise PodWireError(f"{self.kind} transport is not connected")
        return send_frame(self.sock, obj)

    def recv_frame(self) -> dict:
        if self.sock is None:
            raise PodWireError(f"{self.kind} transport is not connected")
        return recv_frame(self.sock)

    def close(self) -> None:
        s, self.sock = self.sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        state = "connected" if self.connected else "idle"
        return f"<{type(self).__name__} {self.address!r} {state}>"


class UnixTransport(Transport):
    """AF_UNIX stream transport — the PR-15 single-host wire."""

    kind = "unix"
    _family = socket.AF_UNIX

    def __init__(self, path: str):
        super().__init__(str(path))


class TcpTransport(Transport):
    """TCP transport for multi-host fleets. Loopback-only in this tree
    (the worker binds 127.0.0.1 and hands the kernel-chosen port back
    through its port file + hello echo); NODELAY is set because every
    frame is a complete request/reply — Nagle would serialize the tick
    cadence behind delayed acks for zero batching benefit."""

    kind = "tcp"
    _family = socket.AF_INET

    def __init__(self, address: tuple[str, int]):
        host, port = address
        super().__init__((str(host), int(port)))

    def connect(self, timeout_s: float | None = None) -> "Transport":
        super().connect(timeout_s)
        assert self.sock is not None
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self


def make_transport(kind: str, address) -> Transport:
    """Build the transport for `kind` ("unix" | "tcp"). The address is
    a socket path for unix, a (host, port) pair for tcp."""
    if kind == "unix":
        return UnixTransport(address)
    if kind == "tcp":
        return TcpTransport(address)
    raise ValueError(f"unknown pod transport kind: {kind!r}")


# --------------------------------------------------------- chain codec


def _b64(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {
        "shape": list(a.shape),
        "dtype": str(a.dtype),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _unb64(spec: dict) -> np.ndarray:
    raw = base64.b64decode(spec["b64"])
    return np.frombuffer(raw, dtype=np.dtype(spec["dtype"])).reshape(
        spec["shape"]).copy()


def _payload_sha256(ids: np.ndarray, kv: dict[str, np.ndarray]) -> str:
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(ids, np.int32).tobytes())
    for path in sorted(kv):
        h.update(path.encode("utf-8"))
        h.update(np.ascontiguousarray(kv[path]).tobytes())
    return h.hexdigest()


def serialize_chain(pool, refs: list[bytes]) -> dict:
    """Serialize a HELD chain (the caller keeps its refs until the
    receiver confirms adoption) into a JSON-safe dict: ids + per-leaf
    K/V + the pool's content digests + a sha256 over the raw bytes."""
    ids, kv = pool.gather(refs)
    return {
        "n": int(ids.size),
        "ids": _b64(np.asarray(ids, np.int32)),
        "kv": {path: _b64(a) for path, a in kv.items()},
        "refs": [d.hex() for d in refs],
        "sha256": _payload_sha256(ids, kv),
    }


def deserialize_chain(pool, payload: dict):
    """Re-insert a serialized chain into `pool` and return a
    SequenceChain holding the produced refs.

    Integrity is checked twice: the sha256 over the decoded arrays must
    match the sender's, and — when the local insert covered every
    position — the content digests the local pool produced must equal
    the digests the sender claimed (they are the same sha1 chain over
    the same ids, so inequality means corruption, not divergence). An
    insert that stops early (covered-by-sibling / partial-parent in the
    receiving pool) yields a FROZEN chain, which the engine's resume
    validation rejects — the requeue then falls back to scratch, never
    to silently wrong K/V. Raises PodWireError on integrity failure."""
    from kubeflow_tpu.serving.fleet.pagedkv import SequenceChain

    ids = _unb64(payload["ids"])
    if ids.size != int(payload["n"]):
        raise PodWireError(
            f"chain length mismatch: {ids.size} ids vs n={payload['n']}")
    kv = {path: _unb64(spec) for path, spec in payload["kv"].items()}
    got = _payload_sha256(ids, kv)
    if got != payload["sha256"]:
        raise PodWireError(
            f"chain payload sha256 mismatch ({got[:12]} != "
            f"{str(payload['sha256'])[:12]})")
    held = pool.insert(ids, kv)
    chain = SequenceChain(pool, held, expect_length=int(payload["n"]))
    if not chain.frozen:
        claimed = list(payload.get("refs", ()))
        if claimed and [d.hex() for d in held] != claimed:
            chain.release()
            raise PodWireError("chain digest mismatch after re-insert")
    return chain


# ----------------------------------------------------------- envelopes


def error_reply(seq: int, code: int, msg: str,
                retry_after_s: float | None = None) -> dict:
    rep: dict[str, Any] = {F_SEQ: seq, F_OK: False,
                           F_CODE: int(code), F_ERROR: str(msg)}
    if retry_after_s is not None:
        rep[F_RETRY_AFTER_S] = float(retry_after_s)
    return rep


def ok_reply(seq: int, **result) -> dict:
    rep: dict[str, Any] = {F_SEQ: seq, F_OK: True}
    rep.update(result)
    return rep
