"""Pod client — the router-side engine facade over a subprocess pod.

spawn_pod() launches ``python -m kubeflow_tpu.serving.fleet.podworker``
and returns a PodClient that quacks exactly like the ContinuousBatcher
surface the FleetRouter consumes (submit/tick/start/stop/_fail_all,
`_queue`/`_rows`/`paged_kv`/counter mirrors) — so a Replica whose engine
is a real subprocess is indistinguishable to the routing, requeue,
autoscaling, and load-test layers. What changes is the failure model:

  - every wire call rides utils/retry (BackoffPolicy + per-op Deadline
    propagated in the envelope as REMAINING seconds; 503 replies honor
    the worker's Retry-After hint via hinted_sleep); exhaustion — or a
    vanished process — escalates to pod death;
  - pod death fires `on_death` (wire_pod_deaths flips the Replica
    under router._mu) and then fails every local handle, whose on_done
    callbacks drive the router's zero-drop requeue exactly like an
    in-process _fail_all;
  - the paged-KV handoff crosses the process boundary: a prefill pod's
    finished chain arrives serialized in its done event and is
    re-inserted (digest-cross-checked) into the ROUTER-side home pool;
    a decode-leg dispatch serializes the home chain into the submit
    payload and KEEPS the home refs as the handle's recovery chain —
    on pod death that surviving chain transfers to the handle, and the
    router's token record resumes the decode with zero re-prefill.
    The home pool is shared by every PodClient of a fleet, so the
    router's resume-pool invariant holds unchanged.

Transport: the wire rides a wire.Transport — AF_UNIX (single-host) or
TCP (multi-host; the worker binds 127.0.0.1:0, publishes the port
atomically through its port file, and echoes it in the hello). A TCP
fleet inherits the network's failure family, so the client grows three
orthogonal states beyond `dead`:

  - `partitioned`: the host is unreachable — wire ops fail without
    touching the socket, retries exhaust into death, and death paths
    SKIP the process kill (you cannot signal a host you cannot reach);
    the worker survives the partition, which is the split-brain hazard;
  - `fenced`: this client's claim on the replica identity is over (the
    scaler replaced it, or the worker answered 410 to a stale epoch).
    A fenced client refuses every late ack/token the healed wire could
    still deliver (counted kftpu_pod_net_fenced_frames_total) — the
    router-side half of epoch fencing;
  - reconnects: _ensure_conn redials transparently inside the envelope
    Deadline; replays are exact because submits are rid-deduped and the
    outbox is cumulative-acked (a reconnect never replays tokens or
    drops acks). Redials after an established connection count
    kftpu_pod_net_reconnects_total.

Locking: `_wire_mu` (socket) is a LEAF — nothing else is ever taken
under it; `_tick_mu` serializes tick rounds and event dispatch and may
reach router._mu through callbacks; `_lock` guards the handle table
only. submit() runs UNDER router._mu, so its failure path never fires
callbacks — it marks the pod quietly dead and raises PodDead for the
router's dispatch loop to re-pick (death propagation happens after _mu
is released).
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.analysis.protocheck.eventlog import log_event
from kubeflow_tpu.serving.fleet.wire import (
    CODE_BUSY,
    CODE_CONFLICT,
    CODE_DEADLINE,
    CODE_FENCED,
    CODE_INTERNAL,
    EV_DONE,
    EV_TOKEN,
    F_ACK,
    F_CHAIN,
    F_CODE,
    F_DEADLINE_S,
    F_DEPTH,
    F_EOS,
    F_EPOCH,
    F_ERROR,
    F_EV,
    F_EVENTS,
    F_ID,
    F_KEEP_CHAIN,
    F_MAX_NEW_TOKENS,
    F_N,
    F_OK,
    F_PORT,
    F_PROMPT,
    F_RESUME,
    F_RETRY_AFTER_S,
    F_RID,
    F_SEQ,
    F_STEP_COUNT,
    F_TEMPERATURE,
    F_TICK_ERROR,
    F_TOK,
    F_TOKENS,
    F_VERB,
    F_BUSY,
    PodCallError,
    PodDead,
    PodDeadlineExpired,
    PodWireError,
    Transport,
    VERB_HELLO,
    VERB_KILL,
    VERB_SUBMIT,
    VERB_TICK,
    make_transport,
    serialize_chain,
)
from kubeflow_tpu.utils.envvars import (
    ENV_POD_NAME,
    ENV_POD_PORT_FILE,
    ENV_POD_SOCKET,
    ENV_POD_SPEC,
    ENV_POD_TRANSPORT,
)
from kubeflow_tpu.utils.retry import (
    BackoffPolicy,
    Deadline,
    hinted_sleep,
    poll_until,
    retry_call,
)

#: default wire retry shape: fast, bounded — exhaustion must surface as
#: pod death within a few hundred ms, not park the dispatch path
DEFAULT_WIRE_POLICY = BackoffPolicy(
    base_s=0.02, max_s=0.25, multiplier=2.0, jitter=1.0, max_attempts=5)


# ------------------------------------------------- kftpu_pod_* registry

#: process-global pod metric families (observability.py renders them
#: zero-valued-stable as kftpu_pod_*) — module-global like the
#: checkpoint-verify counters in health.py: pods outlive any single
#: router, and a dead pod's kill must stay counted after its client is
#: garbage
_POD_METRICS = {
    "spawns_total": 0,
    "kills_total": 0,
    "wire_retries_total": 0,
    "wire_retries_exhausted_total": 0,
    "wire_resets_total": 0,
    "deadline_rejects_total": 0,
    "handoff_bytes_total": 0,
    "net_reconnects_total": 0,
    "net_fenced_frames_total": 0,
    "net_duplicate_acks_refused_total": 0,
    "net_partitions_injected_total": 0,
}
_POD_METRICS_MU = make_lock("fleet.pod_metrics._mu")
#: live clients, for the heartbeat-age gauge (discarded on death)
_LIVE_CLIENTS: list["PodClient"] = []

#: the fleet-wide fence epoch — monotonic across every spawn in this
#: controller process, NEVER reset (a reset could hand a replacement an
#: epoch its fenced predecessor already used, which is exactly the
#: split-brain the fence exists to prevent)
_FENCE_EPOCH = 0


def next_fence_epoch() -> int:
    """Claim the next fence epoch. Every spawn_pod takes one, so a
    scaler replacement is BORN with a higher epoch than its victim."""
    global _FENCE_EPOCH
    with _POD_METRICS_MU:
        _FENCE_EPOCH += 1
        return _FENCE_EPOCH


def pod_metric_bump(name: str, n: int = 1) -> None:
    with _POD_METRICS_MU:
        _POD_METRICS[name] = _POD_METRICS.get(name, 0) + int(n)


def pod_metrics_snapshot() -> dict[str, int]:
    with _POD_METRICS_MU:
        return dict(_POD_METRICS)


def reset_pod_metrics() -> None:
    """Test isolation (the golden-exposition reset path)."""
    with _POD_METRICS_MU:
        for k in _POD_METRICS:
            _POD_METRICS[k] = 0
        del _LIVE_CLIENTS[:]


def pod_heartbeat_age_max_s() -> float:
    """Oldest live pod heartbeat in seconds — 0.0 with no live pods or
    no heartbeat contract armed (zero-valued-stable for /metrics)."""
    with _POD_METRICS_MU:
        clients = list(_LIVE_CLIENTS)
    ages = [a for a in (c.heartbeat_age() for c in clients)
            if a is not None]
    return round(max(ages), 6) if ages else 0.0


def _register_live(client: "PodClient") -> None:
    with _POD_METRICS_MU:
        if client not in _LIVE_CLIENTS:
            _LIVE_CLIENTS.append(client)


def _unregister_live(client: "PodClient") -> None:
    with _POD_METRICS_MU:
        if client in _LIVE_CLIENTS:
            _LIVE_CLIENTS.remove(client)


def _chain_payload_bytes(ser: dict) -> int:
    """Approximate wire size of a serialized chain (the b64 bodies are
    >99% of the frame) — the kftpu_pod_handoff_bytes_total unit."""
    n = len(ser.get("ids", {}).get("b64", ""))
    for spec in ser.get("kv", {}).values():
        n += len(spec.get("b64", ""))
    return n


# ------------------------------------------------------------- handles


class PodHandle:
    """The client-side mirror of a worker _InFlight row: same streaming
    and timing surface (the router's callbacks and the load-test
    collector read these), fed from the pod's event stream."""

    __slots__ = (
        "slot", "request_id", "rid", "max_new_tokens", "tokens", "done",
        "error", "t_submit", "t_first", "t_done", "on_token", "on_done",
        "trace_ctx", "chain", "resumed", "recovery_chain",
    )

    def __init__(self, rid: str, max_new_tokens: int,
                 on_token=None, on_done=None, trace_ctx=None,
                 request_id: str = ""):
        self.slot = -1
        self.rid = rid
        self.request_id = request_id
        self.max_new_tokens = int(max_new_tokens)
        self.tokens: list[int] = []
        self.done = threading.Event()
        self.error: str | None = None
        self.t_submit = time.perf_counter()
        self.t_first: float | None = None
        self.t_done: float | None = None
        self.on_token = on_token
        self.on_done = on_done
        self.trace_ctx = trace_ctx
        #: a chain whose ownership transferred TO this handle (adopted
        #: prefill handoff, or the recovery chain on pod death) — the
        #: router's _on_done consumes or releases it
        self.chain = None
        self.resumed = False
        #: the HOME-pool chain backing a decode-leg resume: held (not
        #: released) until the pod finishes, so a SIGKILL mid-decode
        #: still has the surviving blocks to resume from
        self.recovery_chain = None

    def push(self, tok: int) -> None:
        if not self.tokens:
            self.t_first = time.perf_counter()
        self.tokens.append(int(tok))
        if self.on_token is not None:
            self.on_token(self, tok)

    def finish(self, error: str | None = None) -> None:
        if self.done.is_set():
            return
        self.error = error if self.error is None else self.error
        self.t_done = time.perf_counter()
        self.done.set()
        if self.on_done is not None:
            self.on_done(self)

    @property
    def ttft_s(self) -> float | None:
        return None if self.t_first is None \
            else self.t_first - self.t_submit

    @property
    def tokens_per_s(self) -> float | None:
        if self.t_first is None or self.t_done is None:
            return None
        dt = self.t_done - self.t_first
        return len(self.tokens) / dt if dt > 0 else float("inf")

    def result(self, timeout: float | None = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise RuntimeError(f"generation failed: {self.error}")
        return np.asarray(self.tokens, np.int32)


# -------------------------------------------------------------- client


class PodClient:
    """Engine facade over one worker process (see module docstring)."""

    def __init__(self, name: str, socket_path: str, *,
                 proc: "subprocess.Popen | None" = None,
                 heartbeat_path: str | None = None,
                 stderr_path: str | None = None,
                 policy: BackoffPolicy | None = None,
                 op_timeout_s: float = 30.0,
                 ticks_per_call: int = 1,
                 chaos=None,
                 transport: str = "unix",
                 port_file: str | None = None,
                 epoch: int = 0):
        self.name = name
        self.socket_path = socket_path
        self.transport_kind = transport
        self.port_file = port_file
        self.epoch = int(epoch)
        self.proc = proc
        self.heartbeat_path = heartbeat_path
        self.stderr_path = stderr_path
        self.policy = policy or DEFAULT_WIRE_POLICY
        self.op_timeout_s = float(op_timeout_s)
        self.ticks_per_call = max(int(ticks_per_call), 1)
        self.chaos = chaos
        self._rng = random.Random(f"kftpu-pod-{name}")
        # --- engine facade surface the Replica/router reads
        self._queue: list = []          # always empty: rows seat remotely
        self._rows: list[PodHandle] = []
        self._lock = make_lock("fleet.PodClient._lock")
        self.paged_kv = None            # the router-side HOME pool
        self.tracer = None
        self.tsdb = None
        self._fleet_managed = False
        self.step_count = 0
        self.prefill_tokens_total = 0
        self.prefill_tokens_reused = 0
        self.default_max_new_tokens = 32
        self.eos_token_id: tuple[int, ...] | None = None
        self.worker_pid: int | None = None
        # --- wire state
        self._wire_mu = make_lock("fleet.PodClient._wire_mu")
        self._tick_mu = make_lock("fleet.PodClient._tick_mu")
        self._transport: Transport | None = None
        self._ever_connected = False
        self._port: int | None = None      # discovered TCP port
        self._seq = 0
        self._acked = 0
        self._rid_counter = 0
        self._by_rid: dict[str, PodHandle] = {}
        self._worker_depth = 0
        # --- death state
        self.dead = False
        self.dead_reason: str | None = None
        self._death_propagated = False
        self.on_death = None
        # --- network state (module docstring: the TCP failure family)
        self.partitioned = False
        self.fenced = False
        self.fence_reason: str | None = None
        #: the worker process belongs to a SUCCESSOR's claim (fenced by
        #: a 410) — death paths must not kill it out from under the new
        #: owner. Distinct from `fenced`: a local _fail_all fences too,
        #: but the process is ours and reachable, so it still dies.
        self._disowned = False
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------- wire ops

    def _close_socket(self) -> None:
        t, self._transport = self._transport, None
        if t is not None:
            t.close()

    def _resolve_port(self) -> int:
        """Discover the TCP port the worker published (its port file is
        written atomically AFTER the bind, so a readable file IS a
        listening socket)."""
        if self._port is not None:
            return self._port
        if not self.port_file:
            raise PodWireError(
                f"pod {self.name}: tcp transport without a port file")
        try:
            with open(self.port_file, encoding="utf-8") as fh:
                self._port = int(fh.read().strip())
        except (OSError, ValueError) as e:
            raise PodWireError(f"port file unreadable: {e}") from e
        return self._port

    def _ensure_conn(self, timeout_s: float) -> Transport:
        """The connection supervisor: dial (or redial) the worker. A
        redial after an ESTABLISHED connection is a reconnect — counted,
        because every one of them exercised the replay contract."""
        if self._transport is None:
            if self.transport_kind == "tcp":
                address = ("127.0.0.1", self._resolve_port())
            else:
                address = self.socket_path
            t = make_transport(self.transport_kind, address)
            try:
                t.connect(timeout_s)
            except OSError as e:
                raise PodWireError(f"connect failed: {e}") from e
            self._transport = t
            if self._ever_connected:
                pod_metric_bump("net_reconnects_total")
            self._ever_connected = True
        else:
            self._transport.settimeout(timeout_s)
        return self._transport

    def _attempt(self, verb: str, payload: dict,
                 deadline: Deadline | None, timeout_s: float,
                 bypass_fence: bool = False) -> dict:
        if (self.dead or self.fenced) and not bypass_fence:
            raise PodDead(self.dead_reason or self.fence_reason
                          or f"pod {self.name} dead")
        if self.partitioned:
            # unreachable host: nothing crosses the wire in either
            # direction — the retry layer backs off and (inside the
            # Deadline) either outlives the partition or exhausts
            raise PodWireError(
                f"pod {self.name} unreachable (partitioned)")
        fault = self.chaos.on_wire_op() if self.chaos is not None \
            else None
        if isinstance(fault, tuple):  # ("delay", s): stall in flight
            # deliberately unclamped by the deadline — the fault MODELS
            # a stall that overshoots the budget, so the envelope's
            # remaining_s goes non-positive and the worker 504s
            hinted_sleep(fault[1])
        with self._wire_mu:
            if fault == "reset":
                self._close_socket()
                pod_metric_bump("wire_resets_total")
                raise PodWireError("chaos: connection reset")
            if fault in ("partition", "blackhole"):
                # the frame is lost BEFORE delivery (a black hole eats
                # it; a partition never carries it) — the worker sees
                # nothing, so the replay after reconnect is the first
                # delivery, not a duplicate
                self._close_socket()
                raise PodWireError(f"chaos: {fault} (frame lost)")
            self._seq += 1
            env = {F_VERB: verb, F_SEQ: self._seq, F_EPOCH: self.epoch,
                   F_DEADLINE_S: (deadline.remaining()
                                  if deadline is not None else None)}
            env.update(payload)
            if fault == "dup" and F_ACK in payload:
                # duplicate delivery, modeled at its true cause: the
                # previous ack is lost in flight, so the worker's outbox
                # keeps everything the client already applied and
                # redelivers it — the id-filter refuses every copy
                # (kftpu_pod_net_duplicate_acks_refused_total)
                env[F_ACK] = 0
            try:
                tr = self._ensure_conn(timeout_s)
                tr.send_frame(env)
                if fault == "halfopen":
                    # half-open connection: the frame WAS delivered (the
                    # worker processes it) but the reply never comes —
                    # the retry replays the verb, and only rid-dedup +
                    # cumulative acks keep that replay exact
                    self._close_socket()
                    raise PodWireError(
                        "chaos: half-open connection (reply lost)")
                if fault == "torn":
                    # truncate the reply mid-read, then drop the
                    # connection: exactly the partial frame the length
                    # prefix exists to detect
                    tr.sock.recv(2)
                    self._close_socket()
                    raise PodWireError("chaos: torn frame")
                reply = tr.recv_frame()
            except OSError as e:
                self._close_socket()
                raise PodWireError(f"{type(e).__name__}: {e}") from e
            except PodWireError:
                self._close_socket()
                raise
            if int(reply.get(F_SEQ, -1)) != self._seq:
                self._close_socket()
                raise PodWireError(
                    f"reply seq {reply.get(F_SEQ)} != {self._seq}")
        if reply.get(F_OK):
            return reply
        code = int(reply.get(F_CODE, CODE_INTERNAL))
        if code == CODE_FENCED:
            # the worker adopted a NEWER epoch: this client's claim on
            # the replica identity is over. Fence (terminal — late
            # events will be refused) but never kill the process: it
            # now belongs to the successor's claim.
            pod_metric_bump("net_fenced_frames_total")
            log_event("wire", "client", "fenced", epoch=self.epoch,
                      pod=self.name)
            self._disowned = True
            # free the wire at once: the worker serves one connection
            # at a time, and holding this one would starve the very
            # successor whose epoch just outranked us
            self._close_socket()
            self.fence(f"worker refused stale epoch {self.epoch}: "
                       f"{reply.get(F_ERROR, code)}")
            raise PodDead(
                f"pod {self.name} fenced: {reply.get(F_ERROR, code)}")
        if code == CODE_BUSY:
            # server-side backpressure: honor Retry-After within the
            # caller's budget, then let the retry layer re-dial
            if hinted_sleep(float(reply.get(F_RETRY_AFTER_S, 0.05)),
                            cap_s=1.0, deadline=deadline):
                raise PodWireError("overloaded (retry-after taken)")
            raise PodDeadlineExpired(
                "overloaded and no budget left for Retry-After")
        if code == CODE_DEADLINE:
            pod_metric_bump("deadline_rejects_total")
            raise PodDeadlineExpired(reply.get(F_ERROR, "deadline"))
        raise PodCallError(code, reply.get(F_ERROR, "pod call failed"))

    def call(self, verb: str, payload: dict | None = None, *,
             deadline: Deadline | None = None,
             timeout_s: float | None = None,
             _bypass_fence: bool = False) -> dict:
        """One wire verb under the retry policy. Raises PodWireError on
        exhausted transport faults, PodDeadlineExpired on a spent
        budget, PodCallError on an application refusal, PodDead once
        the pod is marked dead."""
        attempts = 0
        t = timeout_s if timeout_s is not None else self.op_timeout_s

        def attempt():
            nonlocal attempts
            attempts += 1
            return self._attempt(verb, dict(payload or {}), deadline, t,
                                 bypass_fence=_bypass_fence)

        try:
            out = retry_call(attempt, policy=self.policy,
                             retry_on=(PodWireError,), rng=self._rng)
        except PodWireError:
            # exhaustion escalating to pod death: the N absorbed faults
            # stay OUT of wire_retries (that family counts only faults
            # the retry layer actually rode through — the serve_pods
            # gate pins it 0 on a healthy tree) but the give-up itself
            # must be visible on /metrics, not just as a kills_total
            # increment with no cause attached
            pod_metric_bump("wire_retries_exhausted_total")
            raise
        if attempts > 1:
            pod_metric_bump("wire_retries_total", attempts - 1)
        return out

    # ---------------------------------------------------------- spawn

    def connect(self, timeout_s: float = 180.0) -> "PodClient":
        """Wait for the worker's rendezvous artifact — the AF_UNIX
        socket path, or the TCP port file (both appear only after the
        in-process warmup) — and complete the hello handshake. On TCP
        the hello echoes the worker's bound port, which must match the
        discovered one (a stale port file from a previous incarnation
        would otherwise silently dial a stranger)."""
        rendezvous = (self.port_file if self.transport_kind == "tcp"
                      else self.socket_path)

        def ready():
            if self.proc is not None and self.proc.poll() is not None:
                raise PodDead(
                    f"pod {self.name} exited rc={self.proc.returncode} "
                    f"before ready (stderr: {self.stderr_path})")
            return True if (rendezvous
                            and os.path.exists(rendezvous)) else None

        poll_until(ready, timeout_s=timeout_s,
                   describe=f"pod {self.name} {self.transport_kind} "
                            f"rendezvous")
        hello = self.call(VERB_HELLO,
                          timeout_s=max(self.op_timeout_s, 10.0))
        if self.transport_kind == "tcp":
            echoed = hello.get(F_PORT)
            if echoed is not None and self._port is not None \
                    and int(echoed) != self._port:
                raise PodDead(
                    f"pod {self.name} hello port {echoed} != "
                    f"discovered {self._port}")
        self.worker_pid = int(hello["pid"])
        self.default_max_new_tokens = int(
            hello["default_max_new_tokens"])
        eos = hello.get("eos_token_id")
        self.eos_token_id = tuple(int(t) for t in eos) if eos else None
        _register_live(self)
        return self

    @property
    def pid(self) -> int | None:
        if self.worker_pid is not None:
            return self.worker_pid
        return self.proc.pid if self.proc is not None else None

    def heartbeat_age(self) -> float | None:
        """Seconds since the worker's last liveness beat (None without a
        heartbeat contract or before the first beat) — what the
        scaler's hang watch consumes: a SIGSTOPped pod stays alive and
        connected but this age grows without bound."""
        if self.heartbeat_path is None:
            return None
        from kubeflow_tpu.health import read_heartbeat

        hb = read_heartbeat(self.heartbeat_path)
        if hb is None:
            return None
        return max(time.time() - hb.ts, 0.0)

    # -------------------------------------------------- engine facade

    def submit(self, prompt_ids, max_new_tokens: int | None = None,
               eos_token_id=None, temperature: float = 0.0,
               key=None, on_token=None, on_done=None,
               trace_ctx=None, request_id: str = "",
               keep_chain: bool = False, resume_from=None) -> PodHandle:
        """Mirror of ContinuousBatcher.submit over the wire. Runs UNDER
        router._mu on the dispatch path: a wire failure here must not
        fire callbacks (the router holds its own lock) — the pod is
        marked quietly dead and PodDead raised; the router's dispatch
        loop re-picks and propagates the death after releasing _mu."""
        if self.dead or self.fenced:
            raise PodDead(self.dead_reason or self.fence_reason
                          or f"pod {self.name} dead")
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        budget = int(max_new_tokens or self.default_max_new_tokens)
        with self._lock:
            self._rid_counter += 1
            rid = f"{self.name}-{self._rid_counter}"
        eos = eos_token_id
        if eos is not None and not isinstance(eos, (int, np.integer)):
            eos = [int(t) for t in np.asarray(eos).reshape(-1)]
        elif eos is not None:
            eos = int(eos)
        payload = {
            F_RID: rid,
            F_PROMPT: [int(t) for t in ids],
            F_MAX_NEW_TOKENS: budget,
            F_EOS: eos,
            F_TEMPERATURE: float(temperature),
            F_KEEP_CHAIN: bool(keep_chain),
            F_RESUME: None,
        }
        handle = PodHandle(rid, budget, on_token=on_token,
                           on_done=on_done, trace_ctx=trace_ctx,
                           request_id=request_id)
        if resume_from is not None:
            chain, toks = resume_from
            if chain.frozen:
                raise ValueError("cannot resume from a frozen chain")
            if self.paged_kv is not None \
                    and chain.pool is not self.paged_kv:
                raise ValueError(
                    "resume chain lives in a different pool than this "
                    "pod's home pool")
            ser = serialize_chain(chain.pool, chain.refs)
            payload[F_RESUME] = {F_CHAIN: ser,
                                 F_TOKENS: [int(t) for t in toks]}
            pod_metric_bump("handoff_bytes_total",
                            _chain_payload_bytes(ser))
            # the zero-drop collateral: the HOME chain stays held on
            # the handle — a pod death mid-decode transfers it back to
            # the router's requeue instead of losing the blocks
            handle.recovery_chain = chain
            handle.tokens = [int(t) for t in toks]  # pre-fed, no cbs
            handle.resumed = True
            handle.t_first = time.perf_counter()
        try:
            self.call(VERB_SUBMIT, payload)
            log_event("wire", "client", "submit", rid=rid,
                      epoch=self.epoch, resumed=bool(resume_from))
        except (PodWireError, PodDead, OSError) as e:
            self._quiet_dead(f"wire failure during submit: {e}")
            raise PodDead(
                f"pod {self.name} died during submit: {e}") from e
        except PodCallError as e:
            if e.code == CODE_CONFLICT and resume_from is not None:
                # resume refusal (frozen on re-insert in the worker
                # pool): release the recovery hold and fall back to
                # scratch via the router's requeue arithmetic
                handle.recovery_chain = None
                resume_from[0].release()
            raise
        with self._lock:
            self._by_rid[rid] = handle
            self._rows = self._rows + [handle]
        return handle

    def tick(self) -> bool:
        """One tick round-trip: drive the worker's engine, drain its
        event outbox (deduped by cumulative ack — a redelivered event
        after a torn frame is skipped, never double-pushed), mirror its
        counters. Event callbacks run OUTSIDE every client lock."""
        if self.dead:
            return False
        with self._tick_mu:
            if self.dead:
                return False
            try:
                reply = self.call(
                    VERB_TICK,
                    {F_ACK: self._acked, F_N: self.ticks_per_call})
            except (PodWireError, OSError) as e:
                self._mark_dead(f"wire failure during tick: {e}")
                return False
            except PodDead as e:
                if self.fenced and not self.dead:
                    # fenced mid-tick (410): terminal for the replica,
                    # but the PROCESS belongs to the successor now —
                    # _quiet_dead's fenced guard skips the kill
                    self._mark_dead(f"fenced: {e}")
                else:
                    self._propagate_death()
                return False
            if self.fenced or self.dead:
                # the fence raced the round-trip: a kill/replace landed
                # while this frame was in flight. Whatever the reply
                # carries is a LATE delivery from a superseded claim —
                # refuse every event, ack nothing (the router-side half
                # of epoch fencing).
                late = list(reply.get(F_EVENTS, ()))
                if late:
                    pod_metric_bump("net_fenced_frames_total",
                                    len(late))
                return False
            self.step_count = int(
                reply.get(F_STEP_COUNT, self.step_count))
            self.prefill_tokens_total = int(
                reply.get("prefill_tokens_total",
                          self.prefill_tokens_total))
            self.prefill_tokens_reused = int(
                reply.get("prefill_tokens_reused",
                          self.prefill_tokens_reused))
            self._worker_depth = int(reply.get(F_DEPTH, 0))
            raw = list(reply.get(F_EVENTS, ()))
            events = [e for e in raw
                      if int(e.get(F_ID, 0)) > self._acked]
            if len(raw) > len(events):
                # redelivery of already-acked events (a lost ack, a
                # replayed tick after reconnect): each copy is refused
                # by the cumulative-ack filter, never double-pushed
                pod_metric_bump("net_duplicate_acks_refused_total",
                                len(raw) - len(events))
            if events:
                self._acked = int(events[-1][F_ID])
            for ev in events:
                self._apply_event(ev)
            if reply.get(F_TICK_ERROR):
                # poisoned engine: its _fail_all events just drained
                # above; the process itself is now useless — reap it
                self._mark_dead(
                    f"worker engine poisoned: {reply[F_TICK_ERROR]}")
                return False
            return bool(reply.get(F_BUSY)) or bool(self._rows)

    def _apply_event(self, ev: dict) -> None:
        h = self._by_rid.get(str(ev.get(F_RID, "")))
        if h is None or h.done.is_set():
            return
        log_event("wire", "client", "deliver", rid=str(ev.get(F_RID)),
                  id=int(ev.get(F_ID, 0)), kind=str(ev.get(F_EV)),
                  epoch=self.epoch)
        if ev.get(F_EV) == EV_TOKEN:
            h.push(int(ev[F_TOK]))
            return
        if ev.get(F_EV) != EV_DONE:
            return
        # reconcile: the done event's token list is authoritative; any
        # suffix the stream hasn't delivered yet (lost with a torn
        # frame, redelivered here) pushes now
        final = [int(t) for t in ev.get(F_TOKENS, ())]
        for tok in final[len(h.tokens):]:
            h.push(tok)
        error = ev.get(F_ERROR)
        if error is None and ev.get(F_CHAIN) is not None \
                and self.paged_kv is not None:
            from kubeflow_tpu.serving.fleet.wire import deserialize_chain

            try:
                h.chain = deserialize_chain(self.paged_kv, ev[F_CHAIN])
                pod_metric_bump("handoff_bytes_total",
                                _chain_payload_bytes(ev[F_CHAIN]))
            except (PodWireError, KeyError, ValueError):
                h.chain = None  # integrity refusal → scratch fallback
        if error is None and h.recovery_chain is not None:
            # the resumed decode finished — the home-pool hold served
            # its purpose
            h.recovery_chain.release()
            h.recovery_chain = None
        if error is not None:
            self._transfer_recovery(h)
        with self._lock:
            self._by_rid.pop(h.rid, None)
            self._rows = [r for r in self._rows if r is not h]
        h.finish(error=error)

    def _transfer_recovery(self, h: PodHandle) -> None:
        """A failing handle's home-pool recovery chain transfers to
        `h.chain` when the router's requeue is listening (the same
        conditions ContinuousBatcher._fail_all applies) — otherwise the
        hold releases so blocks never leak."""
        chain, h.recovery_chain = h.recovery_chain, None
        if chain is None:
            return
        if h.on_done is not None and self._fleet_managed \
                and not chain.frozen and h.chain is None:
            h.chain = chain
        else:
            chain.release()

    # -------------------------------------------------------- lifecycle

    def start(self) -> "PodClient":
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"pod-client-{self.name}",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            busy = self.tick()
            if self.dead:
                return
            if not busy:
                self._stop_evt.wait(0.002)

    def stop(self) -> None:
        """Stop the client ticker thread. Does NOT kill the pod — the
        router's kill path continues into _fail_all, and a drill's
        clean shutdown uses kill()/drain() explicitly."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Tick until the worker and the local handle table are empty.
        True on drained; False when the budget ran out first."""
        deadline = Deadline(timeout_s)
        while not self.dead:
            self.tick()
            with self._lock:
                local = len(self._rows)
            if local == 0 and self._worker_depth == 0:
                return True
            if deadline.expired():
                return False
        return False

    def kill(self, timeout_s: float = 5.0) -> None:
        """Graceful shutdown: ask the worker to exit, reap, mark dead
        quietly (no requeue callbacks — drain first if rows matter)."""
        try:
            self.call(VERB_KILL, timeout_s=timeout_s)
        except (PodWireError, PodDead, PodDeadlineExpired,
                PodCallError, OSError):
            pass
        self._quiet_dead("killed (graceful)")

    # ------------------------------------------------------------ death

    def _kill_process(self) -> None:
        p = self.proc
        if p is None:
            return
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
        try:
            p.wait(timeout=5.0)
        except (subprocess.TimeoutExpired, OSError):
            pass

    def _quiet_dead(self, reason: str) -> bool:
        """Flip dead, close the wire, reap the process — NO callbacks
        (safe under router._mu). Returns True on the first flip.

        The process kill is SKIPPED for a partitioned or disowned pod:
        an unreachable host cannot be signaled, and a 410-fenced
        worker is already serving its successor's claim — in both
        cases the worker SURVIVES this death, which is exactly the
        split-brain hazard the epoch fence exists to neutralize. (A
        LOCAL fence — _fail_all on a reachable host — still kills: the
        process is ours.)"""
        with self._lock:
            if self.dead:
                return False
            self.dead, self.dead_reason = True, reason
        self._stop_evt.set()
        self._close_socket()
        if self.partitioned or self._disowned:
            # the worker outlives this death — whatever it still holds
            # is a superseded claim and must be refused if the wire
            # ever heals
            self.fence(reason)
        else:
            self._kill_process()
        _unregister_live(self)
        pod_metric_bump("kills_total")
        return True

    def _propagate_death(self) -> None:
        """Fire on_death (the Replica alive flip) then fail every local
        handle — their on_done callbacks drive the router requeue.
        Must be called with NO client or router locks held."""
        with self._lock:
            if self._death_propagated or not self.dead:
                return
            self._death_propagated = True
        if self.on_death is not None:
            self.on_death(self)
        self._fail_local(self.dead_reason or "pod died")

    def _fail_local(self, reason: str) -> None:
        with self._lock:
            rows, self._rows = self._rows, []
            self._by_rid = {}
        for h in rows:
            self._transfer_recovery(h)
            h.finish(error=reason)

    def _mark_dead(self, reason: str) -> None:
        self._quiet_dead(reason)
        self._propagate_death()

    def _fail_all(self, reason: str) -> None:
        """The router's kill_replica contract (after its alive flip):
        terminate the pod and requeue everything it carried. The kill
        decision FENCES first — from this point every late ack/token
        the wire could still deliver (a partition healing after the
        scaler replaced this replica) is a superseded claim and will be
        refused, so the requeued rids can never stream twice."""
        self.fence(reason)
        self._quiet_dead(reason)
        self._propagate_death()

    # ---------------------------------------------------------- fencing

    def fence(self, reason: str) -> None:
        """Permanently fence this client: its claim on the replica
        identity is over (scaler replacement, or a worker 410).
        Idempotent; fencing itself touches no process — whether the
        worker dies is _quiet_dead's decision (it spares partitioned
        and disowned workers). A fenced client refuses every event the
        wire still delivers (net_fenced_frames_total counts each)."""
        with self._lock:
            if self.fenced:
                return
            self.fenced = True
            self.fence_reason = reason

    def set_partitioned(self, value: bool) -> None:
        """Model a network partition to this pod's host: wire ops fail
        without touching the socket (nothing crosses in either
        direction) and death paths skip the process kill — the worker
        keeps running, unreachable. Healing (False) restores the wire;
        whether frames are then ACCEPTED is the fence's decision."""
        if value and not self.partitioned:
            pod_metric_bump("net_partitions_injected_total")
        self.partitioned = bool(value)
        if value:
            with self._wire_mu:
                self._close_socket()

    def fenced_poll(self, timeout_s: float | None = None) -> dict:
        """The split-brain drill's heal probe: one tick round-trip
        against a FENCED pod's still-running worker (bypassing the dead
        gate), receiving whatever late events its outbox holds — and
        refusing every one of them. Nothing is acked, no handle is
        touched; the return value reports what the fenced claim WOULD
        have delivered, which the drill pins as its zero-duplicate
        proof. Raises if the pod is not fenced, PodWireError if the
        worker is unreachable."""
        if not self.fenced:
            raise RuntimeError(f"pod {self.name} is not fenced")
        with self._tick_mu:
            reply = self.call(VERB_TICK, {F_ACK: self._acked, F_N: 1},
                              timeout_s=timeout_s, _bypass_fence=True)
            late = [e for e in reply.get(F_EVENTS, ())
                    if int(e.get(F_ID, 0)) > self._acked]
            if late:
                pod_metric_bump("net_fenced_frames_total", len(late))
            return {
                "late_events": len(late),
                "late_tokens": sum(1 for e in late
                                   if e.get(F_EV) == EV_TOKEN),
                "late_done": sum(1 for e in late
                                 if e.get(F_EV) == EV_DONE),
                "refused": len(late),
            }


# ----------------------------------------------------------- fleet glue


def attach_router_death(client: PodClient, router) -> None:
    """Wire a pod's death to its Replica: flip alive under router._mu
    (by engine identity — survives renames and scaler replacements) so
    _pick and the tick loops exclude the corpse before the requeue
    callbacks start re-dispatching."""

    def on_death(c):
        with router._mu:
            for rep in router.replicas:
                if rep.engine is c and rep.alive:
                    rep.alive = False
                    router.metrics["replica_kills_total"] += 1
                    break

    client.on_death = on_death


def wire_pod_deaths(router) -> None:
    """attach_router_death over every current PodClient replica."""
    for rep in router.replicas:
        if isinstance(rep.engine, PodClient):
            attach_router_death(rep.engine, router)


def spawn_pod(name: str, spec: dict, state_dir: str, *,
              home_pool=None, policy: BackoffPolicy | None = None,
              op_timeout_s: float = 30.0, chaos=None,
              startup_timeout_s: float = 240.0,
              env_extra: dict | None = None,
              connect: bool = True,
              transport: str = "unix") -> PodClient:
    """Launch one worker subprocess and return its connected client.

    The pod env contract rides os.environ (KFTPU_TRACE_DIR /
    KFTPU_TRACEPARENT pass through untouched, so worker spans land in
    the same trace dir the controller merges) plus the pod's own
    socket/name/spec variables and a per-pod heartbeat file; stderr
    goes to `<state_dir>/<name>.stderr.log` for post-mortems.

    transport="tcp" puts the wire on 127.0.0.1 TCP: the worker binds an
    ephemeral port, publishes it through `<state_dir>/<name>.port`, and
    echoes it in the hello. Every spawn claims the next fence epoch, so
    a scaler replacement is born with a higher epoch than its victim —
    the split-brain fence's foundation."""
    os.makedirs(state_dir, exist_ok=True)
    spec_path = os.path.join(state_dir, f"{name}.spec.json")
    with open(spec_path, "w", encoding="utf-8") as fh:
        json.dump(spec, fh)
    sock_path = os.path.join(state_dir, f"{name}.sock")
    port_file = os.path.join(state_dir, f"{name}.port")
    for stale in (sock_path, port_file):
        try:
            os.unlink(stale)
        except OSError:
            pass
    hb_path = os.path.join(state_dir, f"{name}.hb")
    stderr_path = os.path.join(state_dir, f"{name}.stderr.log")
    from kubeflow_tpu.utils.envvars import ENV_HEARTBEAT_FILE

    env = dict(os.environ)
    env[ENV_POD_SOCKET] = sock_path
    env[ENV_POD_NAME] = name
    env[ENV_POD_SPEC] = spec_path
    env[ENV_POD_TRANSPORT] = transport
    if transport == "tcp":
        env[ENV_POD_PORT_FILE] = port_file
    env[ENV_HEARTBEAT_FILE] = hb_path
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    with open(stderr_path, "ab") as errf:
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "kubeflow_tpu.serving.fleet.podworker"],
            env=env, stdin=subprocess.DEVNULL,
            stdout=errf, stderr=errf)
    pod_metric_bump("spawns_total")
    client = PodClient(name, sock_path, proc=proc,
                       heartbeat_path=hb_path, stderr_path=stderr_path,
                       policy=policy, op_timeout_s=op_timeout_s,
                       chaos=chaos, transport=transport,
                       port_file=(port_file if transport == "tcp"
                                  else None),
                       epoch=next_fence_epoch())
    client.paged_kv = home_pool
    if connect:
        try:
            client.connect(timeout_s=startup_timeout_s)
        except BaseException:
            client._quiet_dead("startup failed")
            raise
    return client
