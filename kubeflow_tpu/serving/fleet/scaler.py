"""FleetScaler — the closed autoscaling loop over the demand signal.

ROADMAP item 3's missing consumer: PR 9 produced `demand_replicas()` and
PR 12 made it burn-rate-aware (`demand_replicas_burn(monitor)`), but the
signal drove nothing. This controller closes the loop — each
``evaluate()`` pass reads the signal and moves the fleet toward it:

  - **scale-up** builds a replica through ``engine_factory`` (the cold
    start: the factory constructs AND warms the engine, so a fresh
    replica never serves its first request through a compile — the
    readiness-probe contract, and the observed duration feeds the
    cold-start EWMA the activator's Retry-After hints calibrate from);
    a replica still draining is un-drained first — the cheapest
    capacity;
  - **scale-down is a graceful drain**: the target replica stops
    admitting (router.begin_drain), in-flight requests finish in place,
    and the empty shell is reaped; a drain that outlives its grace
    window finishes as a *polite kill_replica* — the PR-13 requeue
    chain-resumes every seated request onto survivors, so scale-down is
    loss-free by construction;
  - **hysteresis**: decisions are counted in EVALUATIONS, not wall
    seconds (machine-invariant in the tick-driven soak): scale-up obeys
    a cooldown, scale-down needs the demand to sit low for
    ``scale_down_stable_evals`` consecutive passes — a chaos-induced
    burn spike can raise the fleet but can never thrash it;
  - **scale-to-zero / wake-on-arrival**: with ``min_replicas=0`` an
    idle fleet drains to nothing after ``idle_to_zero_evals``; the
    first arrival is shed with Retry-After but stamps the router's wake
    signal (`_pick`), which the next evaluation answers with a
    cold-started replica — the activator scale-from-zero path,
    in-process;
  - **hang detection**: a replica holding work whose engine makes no
    step progress across ``hang_detect_evals`` passes is declared hung
    and politely killed (the liveness layer's lease-expiry contract,
    serving edition) — after a replacement is up if it was the last.
    Indictment requires PEER progress (some other replica advanced, or
    the suspect is the only one): a fleet-WIDE stall is systemic and
    killing through it converts the stall into dropped requests (the
    health.py straggler contract, fleet edition). Corollary: the
    caller's scheduler must drive every live replica each pass (the
    loadtest/soak/threaded contract) — a driver that starves a subset
    is indistinguishable from real hangs on exactly that subset.

Every decision is traced: a ``scaler.evaluate`` event carries the
demand/burn inputs, and the ``fleet.scale_up`` / ``fleet.scale_down``
events (and any drain-timeout ``fleet.replica_kill``) parent-link to the
evaluation that triggered them — `profiling.analytics.scaler_shape`
renders the golden-pinnable structural text. Counters surface as
``kftpu_scaler_*`` in /metrics (docs/autoscaling.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.tracing.core import armed_tracer

#: EWMA weight of each observed cold-start duration
_COLD_ALPHA = 0.3


@dataclass(frozen=True)
class ScalerConfig:
    """Knobs of the scaling loop (docs/autoscaling.md). All hysteresis
    windows are counted in evaluate() passes: the caller owns the
    cadence (the soak drives one pass per tick; the ISVC controller one
    per reconcile), so the loop's behavior is cadence-relative and
    machine-speed invariant."""

    min_replicas: int = 0
    max_replicas: int = 8
    #: evaluations between consecutive scale-up decisions
    scale_up_cooldown_evals: int = 2
    #: consecutive below-target evaluations before a scale-down
    scale_down_stable_evals: int = 6
    #: consecutive fully-idle evaluations before scale-to-zero
    #: (only with min_replicas == 0)
    idle_to_zero_evals: int = 12
    #: evaluations a drain may run before it finishes as a polite kill
    drain_grace_evals: int = 8
    #: evaluations a work-holding replica may sit without engine step
    #: progress before it is declared hung and killed
    hang_detect_evals: int = 6
    #: wall-clock heartbeat-age ceiling for pod-backed replicas, seconds
    #: (0 = disabled). An engine exposing heartbeat_age() whose worker
    #: has not beaten for longer than this while holding work is
    #: indicted IMMEDIATELY — a SIGSTOPped pod keeps its socket and its
    #: mirrored step_count frozen, so only the worker-side beat exposes
    #: it faster than hang_detect_evals' stall count
    heartbeat_max_age_s: float = 0.0
    #: replicas added per scale-up decision at most (the step bound the
    #: BURN_DEMAND_CAP multiplier is clamped against)
    max_step_up: int = 2

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < 1 \
                or self.min_replicas > self.max_replicas:
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas (>=1), got "
                f"[{self.min_replicas}, {self.max_replicas}]")
        if self.max_step_up < 1:
            raise ValueError("max_step_up must be >= 1")


class FleetScaler:
    """One scaling loop bound to one FleetRouter (module docstring)."""

    def __init__(self, router, engine_factory, config: ScalerConfig |
                 None = None, monitor=None, tracer=None,
                 threaded: bool = False, on_release=None,
                 chipsched=None, chips_per_replica: int = 1,
                 tenant: str = "serving", claim_prefix: str = "fleet"):
        """engine_factory() -> a NEW engine, constructed, warmed (first
        dispatch compiled), and sharing the fleet's paged_kv pool when
        the fleet has one (router.add_replica enforces the invariant).
        monitor (monitoring.SLOMonitor): arms the burn-rate-aware signal
        (demand_replicas_burn); None falls back to the queue math.
        tracer: decision spans; defaults to the router's. threaded:
        start() new engines' serving threads (the Platform/ISVC mode;
        the tick-driven soak leaves engines passive). on_release(engine)
        receives each GRACEFULLY-drained engine (emptied, stopped,
        healthy) — the warm-standby recycling hook; killed/hung engines
        never pass through it. chipsched (scheduler.ChipScheduler):
        the shared chip ledger — every cold-started replica claims
        chips_per_replica chips under ``tenant`` before it exists
        (preemption-then-grant: a claim that cannot fit evicts the
        lowest-priority batch gang), and every removal releases them; a
        deny is traced (sched.deny) and counted while the burn signal
        keeps demanding. None = no chip accounting (standalone fleets,
        the pre-ledger contract)."""
        self.router = router
        self.engine_factory = engine_factory
        self.on_release = on_release
        self.chipsched = chipsched
        self.chips_per_replica = chips_per_replica
        self.tenant = tenant
        self.claim_prefix = claim_prefix
        self.cfg = config or ScalerConfig()
        self.monitor = monitor
        self.tracer = tracer if tracer is not None else router.tracer
        self.threaded = threaded
        #: chaos hook (KFTPU_PROF_CHAOS="scaler_freeze:1" via the soak):
        #: a frozen scaler keeps evaluating — and counting — but acts on
        #: nothing, which is exactly the outage the SLO burn alert must
        #: catch (tests/test_prof_gate.py pins it)
        self.frozen = False
        self._mu = make_lock("fleet.FleetScaler._mu")
        self._evals = 0
        self._last_scale_up_eval = -(10 ** 9)
        self._low_demand_evals = 0
        self._idle_evals = 0
        self._created = 0
        #: replica name -> {"since": eval index, "ctx": scale_down span
        #: context} for drains in progress
        self._draining: dict[str, dict] = {}
        #: replica name -> (last step_count, stalled evals) hang watch
        self._progress: dict[str, tuple[int, int]] = {}
        self.target_replicas = len(router._admittable())
        self.cold_start_ewma_s = 0.0
        self.metrics = {
            "evaluations_total": 0,
            "frozen_evaluations_total": 0,
            "scale_ups_total": 0,
            "scale_downs_total": 0,
            "replicas_added_total": 0,
            "replicas_removed_total": 0,
            "drains_completed_total": 0,
            "drain_kills_total": 0,
            "hangs_detected_total": 0,
            "scale_to_zero_total": 0,
            "scale_from_zero_total": 0,
            "chip_denies_total": 0,
        }
        #: last Deny from the chip ledger (Retry-After surface): the
        #: caller's hint for when demanding again might succeed
        self.last_deny = None
        router.scaler = self

    # ------------------------------------------------------------ chaos

    def freeze(self) -> None:
        self.frozen = True

    def thaw(self) -> None:
        self.frozen = False

    # ------------------------------------------------------------- loop

    def demand(self) -> tuple[int, float]:
        """(desired replicas, worst serving burn rate) — the burn-aware
        signal when a monitor is wired, the queue math otherwise. Reads
        the monitor's LAST evaluation (callers evaluate() it on their
        own cadence, the PR-12 contract)."""
        burn = 0.0
        if self.monitor is not None:
            base = self.router.demand_replicas_burn(self.monitor)
            for state in self.monitor.describe():
                if state["name"].startswith("serving_"):
                    rates = state.get("burn_rates", {})
                    if rates:
                        burn = max(burn, max(rates.values()))
        else:
            base = self.router.demand_replicas()
        return base, burn

    def evaluate(self) -> dict:
        """One pass of the loop: reap finished work (drains, corpses),
        read the demand signal, and move the fleet toward it under the
        hysteresis rules. Returns the decision record (what a dashboard
        or the soak's journal would log)."""
        with self._mu:
            self.metrics["evaluations_total"] += 1
            self._evals += 1
            i = self._evals
        if self.frozen:
            with self._mu:
                self.metrics["frozen_evaluations_total"] += 1
            return {"eval": i, "frozen": True, "actions": []}
        tr = armed_tracer(self.tracer)
        # the evaluation event is allocated lazily: only a pass that
        # ACTS records one, so the trace carries decisions, not heartbeat
        # noise — every action of this pass parent-links to it
        ev = {"ctx": None}

        def eval_ctx(demand, burn, decision):
            if tr is None:
                return None
            if ev["ctx"] is None:
                e = tr.event("scaler.evaluate", parent=None,
                             demand=demand, burn=round(burn, 3),
                             decision=decision,
                             alive=len(self.router._admittable()))
                ev["ctx"] = e.context
            return ev["ctx"]

        actions: list[str] = []
        self._reap_corpses()
        self._watch_hangs(i, tr, eval_ctx, actions)
        self._advance_drains(i, tr, actions)
        demand, burn = self.demand()
        target = min(max(demand, self.cfg.min_replicas),
                     self.cfg.max_replicas)
        serving = self.router._admittable()
        n_serving = len(serving)

        # ---- scale-up (cooldown-gated; un-drain before cold-starting)
        if target > n_serving \
                and i - self._last_scale_up_eval \
                >= self.cfg.scale_up_cooldown_evals:
            need = min(target - n_serving, self.cfg.max_step_up)
            from_zero = n_serving == 0
            ctx = eval_ctx(demand, burn, "scale_up")
            for _ in range(need):
                if not self._scale_up_one(tr, ctx, from_zero=from_zero):
                    break  # chip deny: stop burning claims this pass
                from_zero = False
            self._last_scale_up_eval = i
            self._low_demand_evals = 0
            self._idle_evals = 0
            with self._mu:
                self.metrics["scale_ups_total"] += 1
            self.router.clear_wake()
            actions.append(f"scale_up x{need}")

        # ---- scale-down (stability-gated graceful drain, one at a time)
        elif target < n_serving:
            self._low_demand_evals += 1
            if self._low_demand_evals >= self.cfg.scale_down_stable_evals \
                    and n_serving > max(target, 1):
                victim = min(serving, key=lambda r: r.pending_tokens())
                ctx = eval_ctx(demand, burn, "scale_down")
                self._begin_drain(victim, i, tr, ctx, reason="demand")
                self._low_demand_evals = 0
                actions.append(f"drain {victim.name}")
        else:
            self._low_demand_evals = 0

        # ---- scale-to-zero (idle-gated; min_replicas == 0 only).
        # Idleness is measured on the FLEET (no seated work, no wake
        # arrivals), not on the demand signal — demand floors at 1
        # while any replica serves, by design (test_fleet pins it)
        idle = (self.router.wake_pending() == 0
                and all(r.depth() == 0 for r in self.router._alive()))
        self._idle_evals = self._idle_evals + 1 if idle else 0
        if (self.cfg.min_replicas == 0 and idle
                and self._idle_evals >= self.cfg.idle_to_zero_evals
                and self.router._admittable()):
            ctx = eval_ctx(demand, burn, "scale_to_zero")
            for rep in list(self.router._admittable()):
                self._begin_drain(rep, i, tr, ctx, reason="scale_to_zero")
            with self._mu:
                self.metrics["scale_to_zero_total"] += 1
            self._idle_evals = 0
            actions.append("scale_to_zero")

        self.target_replicas = target
        return {"eval": i, "frozen": False, "demand": demand,
                "burn": round(burn, 4), "target": target,
                "serving": len(self.router._admittable()),
                "draining": len(self._draining), "actions": actions}

    # -------------------------------------------------------- sub-steps

    def _scale_up_one(self, tr, ctx, from_zero: bool) -> bool:
        # a draining replica is capacity we already own: cancel a drain
        # instead of paying a cold start — the one with the MOST seated
        # work (it has the most to lose to a drain-grace polite kill;
        # the emptiest is about to be reaped anyway and costs nothing).
        # Its chip claim was never released (that happens in _remove),
        # so no new claim is needed.
        if self._draining:
            def seated(name):
                try:
                    return self.router._resolve(name).depth()
                except StopIteration:
                    return -1
            dname = max(self._draining, key=seated)
            self.router.cancel_drain(dname)
            self._draining.pop(dname)
            if tr is not None:
                tr.event("fleet.scale_up", parent=ctx, replica=dname,
                         undrained=True, cold_start_s=0.0)
            with self._mu:
                self.metrics["replicas_added_total"] += 1
            return True
        name = f"scaled-{self._created}"
        # a cold start claims its chips FIRST: the shared ledger may
        # preempt a batch gang to make room (preemption-then-grant); a
        # deny leaves the fleet as-is — the burn signal keeps demanding
        # and the Deny's retry_after_s is the caller's hint
        if self.chipsched is not None:
            res = self.chipsched.claim_replica(
                self._claim_key(name), chips=self.chips_per_replica,
                tenant=self.tenant)
            if not res.ok:
                self.last_deny = res
                with self._mu:
                    self.metrics["chip_denies_total"] += 1
                if tr is not None:
                    tr.event("fleet.scale_up_denied", parent=ctx,
                             replica=name, reason=res.reason,
                             retry_after_s=res.retry_after_s)
                return False
        t0 = time.perf_counter()
        engine = self.engine_factory()
        self._created += 1
        rep = self.router.add_replica(engine, name=name)
        if self.threaded:
            engine.start()
        dt = time.perf_counter() - t0
        self.cold_start_ewma_s = (
            dt if self.cold_start_ewma_s <= 0.0
            else (1 - _COLD_ALPHA) * self.cold_start_ewma_s
            + _COLD_ALPHA * dt)
        with self._mu:
            self.metrics["replicas_added_total"] += 1
            if from_zero:
                self.metrics["scale_from_zero_total"] += 1
        if tr is not None:
            tr.event("fleet.scale_up", parent=ctx, replica=rep.name,
                     from_zero=from_zero, cold_start_s=round(dt, 4))
        return True

    def _claim_key(self, replica_name: str) -> str:
        return f"{self.claim_prefix}/{replica_name}"

    def _begin_drain(self, rep, eval_i: int, tr, ctx,
                     reason: str) -> None:
        self.router.begin_drain(rep.name)
        self._draining[rep.name] = {"since": eval_i, "ctx": ctx}
        with self._mu:
            self.metrics["scale_downs_total"] += 1
        if tr is not None:
            tr.event("fleet.scale_down", parent=ctx, replica=rep.name,
                     reason=reason, in_flight=rep.depth())

    def _advance_drains(self, eval_i: int, tr, actions: list) -> None:
        for name, st in list(self._draining.items()):
            try:
                rep = self.router._resolve(name)
            except StopIteration:
                self._draining.pop(name)
                continue
            if not rep.alive:
                # chaos killed it mid-drain: the requeue already rescued
                # its work — just reap the corpse
                self._remove(rep)
                self._draining.pop(name)
                continue
            if rep.depth() == 0:
                rep.engine.stop()
                self._remove(rep)
                self._draining.pop(name)
                with self._mu:
                    self.metrics["drains_completed_total"] += 1
                if self.on_release is not None:
                    self.on_release(rep.engine)
                actions.append(f"drained {name}")
            elif eval_i - st["since"] >= self.cfg.drain_grace_evals:
                # grace expired with rows still seated: finish the drain
                # as a polite kill — every request chain-resumes onto a
                # survivor (zero drops, zero re-decode when the pool
                # held its chain)
                self.router.kill_replica(name, parent=st["ctx"])
                self._remove(rep)
                self._draining.pop(name)
                with self._mu:
                    self.metrics["drain_kills_total"] += 1
                actions.append(f"drain_kill {name}")

    def _watch_hangs(self, eval_i: int, tr, eval_ctx, actions) -> None:
        cfg = self.cfg
        watched = [r for r in self.router._alive() if not r.draining]
        advanced = False
        suspects = []
        for rep in watched:
            steps = int(getattr(rep.engine, "step_count", 0))
            last, stalled = self._progress.get(rep.name, (steps, 0))
            if steps != last:
                advanced = True
            stalled = stalled + 1 if (steps == last
                                      and rep.depth() > 0) else 0
            self._progress[rep.name] = (steps, stalled)
            if stalled >= cfg.hang_detect_evals:
                suspects.append((rep, stalled))
            elif cfg.heartbeat_max_age_s > 0.0:
                # pod-backed liveness: the worker beats per tick verb;
                # an age past the ceiling with work seated means the
                # PROCESS is wedged (SIGSTOP, hard page stall) even
                # though the wire and the mirrored counters look merely
                # idle. A fresh beat, conversely, is live evidence for
                # the peer-progress guard below.
                age_fn = getattr(rep.engine, "heartbeat_age", None)
                age = age_fn() if callable(age_fn) else None
                if age is None:
                    pass
                elif age <= cfg.heartbeat_max_age_s:
                    advanced = True
                elif rep.depth() > 0:
                    suspects.append((rep, stalled))
        # the straggler contract (health.py's gang-median, fleet
        # edition): a stalled replica is indicted only against PEER
        # progress — some other replica advanced this pass — or when it
        # is the only replica (the replacement becomes the reference).
        # A fleet-WIDE stall is systemic (the driver stopped ticking, a
        # global wedge): serially hang-killing healthy replicas there
        # burns every request's requeue budget and converts the stall
        # into drops — the failure mode the verify drive caught.
        if not (advanced or len(watched) == 1):
            return
        for rep, stalled in suspects:
            with self._mu:
                self.metrics["hangs_detected_total"] += 1
            ctx = eval_ctx(-1, 0.0, "hang_kill")
            if tr is not None:
                tr.event("fleet.replica_hung", parent=ctx,
                         replica=rep.name, stalled_evals=stalled)
            survivors = [r for r in self.router._admittable()
                         if r.name != rep.name]
            if not survivors:
                self._scale_up_one(tr, ctx, from_zero=False)
            self.router.kill_replica(rep.name, parent=ctx)
            self._remove(rep)
            self._progress.pop(rep.name, None)
            actions.append(f"hang_kill {rep.name}")

    def _reap_corpses(self) -> None:
        """Chaos-killed replicas (router.kill_replica from a drill or
        fault plan) stay in the replica list as dead entries; the scaler
        garbage-collects them so alive == listed and scale-up names
        never collide with tombstones."""
        for rep in list(self.router.replicas):
            if not rep.alive:
                self._remove(rep)
                self._progress.pop(rep.name, None)

    def _remove(self, rep) -> None:
        try:
            self.router.remove_replica(rep.name)
        except (ValueError, StopIteration):
            return
        # every removal funnel: a reaped replica's hang-watch entry
        # must go with it, or months of scale-up/drain cycles (names
        # never reused) leak one entry per replica ever created
        self._progress.pop(rep.name, None)
        # ... and its chip claim returns to the shared pool — the
        # release half of the ledger contract (a preempted batch gang
        # resumes on exactly these chips)
        if self.chipsched is not None:
            self.chipsched.release(self._claim_key(rep.name))
        with self._mu:
            self.metrics["replicas_removed_total"] += 1
