"""kftpu-fleet — the serving tier between the activator and N engines.

ROADMAP item 2 (serving at planetary scale): paged/block KV cache with
prefix reuse (pagedkv.py), queue-depth-aware routing + SLO admission +
replica-kill requeue across N ContinuousBatcher replicas (router.py),
the seeded open-loop load-test harness (loadtest.py — the serving
analogue of the chaos drills), the closed autoscaling loop
(scaler.py: FleetScaler consumes the burn-aware demand signal —
docs/autoscaling.md), and cross-process pod-backed replicas
(podworker.py / podclient.py over the wire.py protocol: each replica a
real subprocess, killed with real signals, with the paged-KV handoff
crossing the process boundary). Chunked prefill lives in the engine
itself (serving/continuous.py `prefill_chunk`); the pool plugs in there
via the engine's `paged_kv` parameter. docs/serving.md is the operator
guide.
"""

from kubeflow_tpu.serving.fleet.loadtest import (
    LoadReport,
    make_prompts,
    run_loadtest,
    run_loadtest_sync,
)
from kubeflow_tpu.serving.fleet.pagedkv import (
    PagedKVPool,
    PrefixMatch,
    SequenceChain,
    extract_prompt_kv,
    make_row_template,
    seed_row_cache,
)
from kubeflow_tpu.serving.fleet.router import (
    FleetOverloaded,
    FleetRequest,
    FleetRouter,
    Replica,
)
from kubeflow_tpu.serving.fleet.podclient import (
    PodClient,
    PodHandle,
    attach_router_death,
    pod_heartbeat_age_max_s,
    pod_metrics_snapshot,
    spawn_pod,
    wire_pod_deaths,
)
from kubeflow_tpu.serving.fleet.scaler import (
    FleetScaler,
    ScalerConfig,
)
from kubeflow_tpu.serving.fleet.wire import (
    PodCallError,
    PodDead,
    PodDeadlineExpired,
    PodWireError,
)

__all__ = [
    "FleetOverloaded",
    "FleetRequest",
    "FleetRouter",
    "FleetScaler",
    "LoadReport",
    "PagedKVPool",
    "PodCallError",
    "PodClient",
    "PodDead",
    "PodDeadlineExpired",
    "PodHandle",
    "PodWireError",
    "PrefixMatch",
    "Replica",
    "ScalerConfig",
    "SequenceChain",
    "attach_router_death",
    "extract_prompt_kv",
    "make_prompts",
    "make_row_template",
    "pod_heartbeat_age_max_s",
    "pod_metrics_snapshot",
    "run_loadtest",
    "run_loadtest_sync",
    "seed_row_cache",
    "spawn_pod",
    "wire_pod_deaths",
]
