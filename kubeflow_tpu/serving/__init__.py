"""Serving subsystem — KServe parity (SURVEY.md §2.5).

InferenceService spec -> predictor replica processes running an XLA-jitted
model behind v1/v2 inference-protocol REST, with storage-initializer model
pulling and controller-managed readiness/self-healing.
"""

from kubeflow_tpu.serving.api import (
    InferenceService,
    InferenceServiceSpec,
    InferenceServiceStatus,
    PredictorRuntime,
    PredictorSpec,
    ExplainerSpec,
    TransformerSpec,
    validate_isvc,
)
from kubeflow_tpu.serving.client import ServingClient
from kubeflow_tpu.serving.controller import InferenceServiceController
from kubeflow_tpu.serving.model import JaxModel, Model, load_model_class, save_predictor
from kubeflow_tpu.serving.server import ModelServer
from kubeflow_tpu.serving.storage import pull_model, resolve_uri

__all__ = [
    "InferenceService",
    "InferenceServiceController",
    "InferenceServiceSpec",
    "InferenceServiceStatus",
    "JaxModel",
    "Model",
    "ModelServer",
    "PredictorRuntime",
    "PredictorSpec",
    "ServingClient",
    "ExplainerSpec",
    "TransformerSpec",
    "load_model_class",
    "pull_model",
    "resolve_uri",
    "save_predictor",
    "validate_isvc",
]
