"""Continuous batching for generative serving — iteration-level scheduling.

SURVEY §2.5 (KServe model server): the reference's serving runtimes process
one request batch at a time, so concurrent generative requests serialize
whole decodes behind each other. TPU redesign of that surface: decode
throughput is HBM-bandwidth-bound — every decode step streams the full
weight set regardless of how many rows ride it — so a half-empty batch
wastes exactly the bandwidth the chip is bound by. The engine (Orca-style
iteration-level scheduling; row slots instead of vLLM paging) keeps ONE
static-shape decode executable hot and splices sequences in and out
BETWEEN steps:

  - admission: a queued prompt prefills into a free row (per-prompt-length
    prefill executable, batch-1), and a jitted row-splice writes that
    row's cache slice + per-row index into the live batch cache
    (models/gpt.py keeps cache_index/pos_index per-row (B,) for exactly
    this)
  - every tick advances ALL in-flight rows one token in one dispatch —
    rows at different depths, one executable
  - rows retire on EOS or their token budget; the slot readmits the next
    queued request without stalling the other rows

Greedy-only (like speculative decoding): each row's output is EXACTLY
generate()'s greedy decode for that prompt alone — per-row position
masking keeps rows independent. (MoE models break that independence:
capacity-limited dispatch couples rows; the engine refuses them.)
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class _InFlight:
    slot: int
    max_new_tokens: int
    eos_token_id: int | None
    tokens: list = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)

    def result(self, timeout: float | None = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        return np.asarray(self.tokens, np.int32)


class ContinuousBatcher:
    """Fixed-row continuous-batching decode engine over a GPTLM.

    submit() enqueues a prompt and returns a handle whose .result() blocks
    for the generated ids; tick() runs one scheduling round (admit + one
    decode step); run_until_idle() drains everything (the synchronous mode
    tests and the bench use); start()/stop() run ticks on a daemon thread
    (the serving mode).
    """

    def __init__(self, module, variables, max_rows: int = 8,
                 default_max_new_tokens: int = 32,
                 eos_token_id: int | None = None):
        cfg = module.cfg
        if getattr(cfg, "moe_experts", 0):
            raise ValueError(
                "continuous batching requires row-independent decode; MoE "
                "capacity dispatch couples rows (drop pattern depends on "
                "batch composition)")
        self.module = module
        self.variables = variables
        self.max_rows = int(max_rows)
        self.max_len = int(cfg.max_len)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.eos_token_id = eos_token_id
        self._lock = threading.Lock()
        self._queue: list[tuple[np.ndarray, _InFlight]] = []
        self._rows: list[_InFlight | None] = [None] * self.max_rows
        self._toks = np.zeros((self.max_rows,), np.int32)
        self._prefill_cache: dict[int, object] = {}  # prompt_len -> jitted
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.step_count = 0  # decode dispatches (the scheduling metric)

        # live batch cache: created by one R-row dummy decode step
        _, cache = module.apply(
            variables, jnp.zeros((self.max_rows, 1), jnp.int32),
            decode=True, mutable=["cache"])
        self._cache = cache["cache"]

        def _splice(big, row, i):
            """Write batch-1 row-cache `row` into slot i of the live
            cache — every leaf's leading dim is the row dim."""
            def leaf(b, r):
                return jax.lax.dynamic_update_slice(
                    b, r.astype(b.dtype), (i,) + (0,) * (b.ndim - 1))
            return jax.tree.map(leaf, big, row)

        self._splice = jax.jit(_splice)

        def _step(cache_col, toks, active):
            logits, new_cache = module.apply(
                {**variables, "cache": cache_col},
                toks[:, None], decode=True, mutable=["cache"])
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            # free rows keep decoding garbage (their slot is overwritten
            # wholesale on admission) — but their index must not creep past
            # max_len, so park it at 0
            def park(path, leaf):
                name = getattr(path[-1], "key", "")
                if name in ("cache_index", "pos_index"):
                    return jnp.where(active, leaf, 0)
                return leaf
            new_cache = jax.tree_util.tree_map_with_path(
                park, new_cache["cache"])
            return nxt, new_cache

        self._step = jax.jit(_step)

    # ---------------------------------------------------------------- API

    def submit(self, prompt_ids, max_new_tokens: int | None = None,
               eos_token_id: int | None = None) -> _InFlight:
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        budget = int(max_new_tokens or self.default_max_new_tokens)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if ids.size + budget > self.max_len:
            raise ValueError(
                f"prompt {ids.size} + max_new_tokens {budget} exceeds "
                f"max_len {self.max_len}")
        req = _InFlight(slot=-1, max_new_tokens=budget,
                        eos_token_id=(self.eos_token_id if eos_token_id
                                      is None else eos_token_id))
        with self._lock:
            self._queue.append((ids, req))
        return req

    def _prefill(self, ids: np.ndarray):
        fn = self._prefill_cache.get(ids.size)
        if fn is None:
            def prefill(x):
                logits, cache = self.module.apply(
                    self.variables, x, decode=True, mutable=["cache"])
                first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return first, cache["cache"]
            fn = self._prefill_cache[ids.size] = jax.jit(prefill)
        return fn(ids[None, :])

    def _retire(self, slot: int) -> None:
        req = self._rows[slot]
        self._rows[slot] = None
        req.done.set()

    def tick(self) -> bool:
        """One scheduling round: admit queued prompts into free rows, then
        advance every in-flight row one token. Returns True if any work
        remains."""
        with self._lock:
            # ---- admission: prefill into free rows -----------------------
            for slot in range(self.max_rows):
                if self._rows[slot] is not None or not self._queue:
                    continue
                ids, req = self._queue.pop(0)
                first, row_cache = self._prefill(ids)
                self._cache = self._splice(
                    self._cache, row_cache, jnp.int32(slot))
                req.slot = slot
                req.tokens.append(int(first[0]))
                self._rows[slot] = req
                self._toks[slot] = int(first[0])
                # the prefill's first token may already finish the row
                if self._finished(req):
                    self._retire(slot)
            active = np.array([r is not None for r in self._rows])
            if not active.any():
                return bool(self._queue)
            # ---- one decode step for every in-flight row -----------------
            nxt, self._cache = self._step(
                self._cache, jnp.asarray(self._toks),
                jnp.asarray(active))
            self.step_count += 1
            nxt = np.asarray(nxt)
            for slot, req in enumerate(self._rows):
                if req is None:
                    continue
                req.tokens.append(int(nxt[slot]))
                self._toks[slot] = int(nxt[slot])
                if self._finished(req):
                    self._retire(slot)
            return bool(self._queue) or any(
                r is not None for r in self._rows)

    @staticmethod
    def _finished(req: _InFlight) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            return True
        return (req.eos_token_id is not None
                and req.tokens[-1] == req.eos_token_id)

    def run_until_idle(self) -> None:
        while self.tick():
            pass

    # ------------------------------------------------------- serving mode

    def start(self) -> "ContinuousBatcher":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self.tick():
                self._stop.wait(0.002)  # idle: poll the queue cheaply

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
