"""Continuous batching for generative serving — iteration-level scheduling.

SURVEY §2.5 (KServe model server): the reference's serving runtimes process
one request batch at a time, so concurrent generative requests serialize
whole decodes behind each other. TPU redesign of that surface: decode
throughput is HBM-bandwidth-bound — every decode step streams the full
weight set regardless of how many rows ride it — so a half-empty batch
wastes exactly the bandwidth the chip is bound by. The engine (Orca-style
iteration-level scheduling; row slots instead of vLLM paging) keeps ONE
static-shape decode executable hot and splices sequences in and out
BETWEEN steps:

  - admission: a queued prompt prefills into a free row (per-prompt-length
    prefill executable, batch-1), and a jitted row-splice writes that
    row's cache slice + per-row index into the live batch cache
    (models/gpt.py keeps cache_index/pos_index per-row (B,) for exactly
    this)
  - every tick advances ALL in-flight rows one token in one dispatch —
    rows at different depths, one executable
  - rows retire on EOS or their token budget; the slot readmits the next
    queued request without stalling the other rows

Greedy rows are EXACTLY generate()'s greedy decode for that prompt alone —
per-row position masking keeps rows independent. (MoE models stay
independent too: the decode path routes DROPLESS — parallel/moe.py — so
no capacity dispatch couples rows.) Sampling rows (per-request
temperature, engine-level top_k) draw
on-device via per-row keys folded from the request key and the row's step
count — deterministic per key, and greedy/sampling rows mix freely in one
batch.

Speculative mode (draft_module/draft_variables/gamma): each tick runs ONE
fused dispatch — gamma chained batch-R draft steps propose, the target
verifies every row's (last + proposals) block in one (R, gamma+1) pass,
and each row rewinds to ITS accepted length through the per-row
cache_index/pos_index vectors (the solo speculative rewind applied
rowwise; models/gpt.py's block write lands each row's verify block at its
own depth). Greedy rows stay target-greedy-exact; temperature>0 rows run
the rowwise Leviathan/Chen rejection scheme (accept with min(1, p_t/p_d),
residual resample, bonus token from p_t — target-distribution-exact),
and both kinds mix in the one executable. Rows emit 1..gamma+1 tokens
per dispatch, the decode-throughput lever on dispatch-floored links.
Rolling caches, prefill buckets, and engine-level top_k on sampled rows
are refused (hazards documented at the guards).
"""

from __future__ import annotations

import threading
import time

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.tracing.core import armed_tracer, current_context
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


def _eos_tuple(eos) -> tuple[int, ...] | None:
    """Normalize an eos spec (int | sequence | None) to a tuple of stop
    ids for host-side retire checks — mirrors models.gpt.eos_id_array."""
    if eos is None:
        return None
    if isinstance(eos, (list, tuple, np.ndarray)):
        ids = tuple(int(x) for x in np.asarray(eos).reshape(-1))
        return ids or None
    return (int(eos),)


@dataclass
class _InFlight:
    slot: int
    max_new_tokens: int
    eos_token_id: tuple[int, ...] | None
    temperature: float = 0.0
    key: object = None  # jax PRNG key for sampling rows
    tokens: list = field(default_factory=list)
    done: threading.Event = field(default_factory=threading.Event)
    error: str | None = None
    # streaming/timing surface (the fleet tier and the load-test harness
    # read these): submit/first-token/done timestamps plus optional
    # callbacks — on_token(req, tok) per emitted token, on_done(req) once
    # at retire OR failure. Callbacks run on the ENGINE thread: keep them
    # cheap and never call back into this engine under its lock.
    t_submit: float = 0.0
    t_first: float | None = None
    t_done: float | None = None
    on_token: object = None
    on_done: object = None
    # request-tracing state (docs/slo.md): trace_ctx is the `request`
    # root span's pre-allocated identity — engine spans (queue wait,
    # prefill chunks, decode window) parent to it as they complete, and
    # the root itself is recorded retroactively at finish() when this
    # engine OWNS it (own_root; a fleet-submitted request's root belongs
    # to the router). Retro recording means no open Span ever rides the
    # ticker thread — an error path cannot leak one.
    trace_ctx: object = None
    parent_ctx: object = None
    own_root: bool = False
    request_id: str = ""
    _tracer: object = None
    _tsdb: object = None
    t_submit_wall: float = 0.0
    t_first_wall: float | None = None
    # paged-KV lifetime state (fleet.pagedkv.SequenceChain): `chain` is
    # set when ownership TRANSFERS to the handle's consumer — a
    # keep_chain retire (the disaggregated prefill→decode handoff) or a
    # replica-kill _fail_all (the resume-from-KV requeue). `resumed`
    # rows continue a chain mid-decode: their pre-fed tokens never
    # re-fire callbacks and their engine-side TTFT is not a first token.
    chain: object = None
    resumed: bool = False
    _resume: object = None          # (SequenceChain, tokens) until seated
    _keep_chain: bool = False

    def push(self, tok: int) -> None:
        """Engine-side token emission — the ONE append path, so TTFT is
        stamped exactly when the first token exists."""
        if not self.tokens:
            self.t_first = time.perf_counter()
            self.t_first_wall = time.time()
        self.tokens.append(tok)
        if self.on_token is not None:
            self.on_token(self, tok)

    def finish(self, error: str | None = None) -> None:
        self.error = error if self.error is None else self.error
        self.t_done = time.perf_counter()
        if self._tsdb is not None and self.error is None \
                and self.ttft_s is not None and not self.resumed:
            # resumed rows have no first token — their t_first marks the
            # resume point and must not pollute the TTFT SLO series
            self._tsdb.record("serving.ttft_s", self.ttft_s)
        tr = self._tracer
        if tr is not None:
            if self.t_first is not None:
                attrs = {"tokens": len(self.tokens)}
                if self.resumed:
                    attrs["resumed"] = True
                if self.error is not None:
                    # a killed replica's partial decode window: real time
                    # spent, tokens discarded by the requeue contract
                    attrs["error"] = self.error
                tr.record_span(
                    "engine.decode", self.t_first_wall,
                    self.t_done - self.t_first, parent=self.trace_ctx,
                    **attrs)
            if self.own_root:
                tr.record_span(
                    "request", self.t_submit_wall,
                    self.t_done - self.t_submit, context=self.trace_ctx,
                    parent=self.parent_ctx,
                    request_id=self.request_id,
                    outcome="failed" if self.error else "completed",
                    tokens=len(self.tokens))
        self.done.set()
        if self.on_done is not None:
            self.on_done(self)

    @property
    def ttft_s(self) -> float | None:
        return None if self.t_first is None else self.t_first - self.t_submit

    @property
    def tokens_per_s(self) -> float | None:
        if self.t_first is None or self.t_done is None:
            return None
        dt = self.t_done - self.t_first
        return len(self.tokens) / dt if dt > 0 else float("inf")

    def result(self, timeout: float | None = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self.error is not None:
            raise RuntimeError(f"generation failed: {self.error}")
        return np.asarray(self.tokens, np.int32)


@dataclass
class _PendingPrefill:
    """A seated row whose prompt is still prefilling (chunked admission):
    the batch-1 row cache being built, the next position to compute, and
    the pool refs backing any reused prefix. With a draft model the
    draft's own batch-1 cache marches through the same chunk schedule
    (d_cache/d_pos) — admission completes when BOTH are done. A resume
    row (`resume`) has its target cache fully seeded from the pool and
    only waits on the draft (no draft: it never pends at all)."""

    req: _InFlight
    ids: np.ndarray
    pos: int
    cache: object
    last_logits: object = None
    match_refs: list = field(default_factory=list)
    d_cache: object = None
    d_pos: int = 0
    resume: bool = False


class ContinuousBatcher:
    """Fixed-row continuous-batching decode engine over a GPTLM.

    submit() enqueues a prompt and returns a handle whose .result() blocks
    for the generated ids; tick() runs one scheduling round (admit + one
    decode step); run_until_idle() drains everything (the synchronous mode
    tests and the bench use); start()/stop() run ticks on a daemon thread
    (the serving mode).
    """

    def __init__(self, module, variables, max_rows: int = 8,
                 default_max_new_tokens: int = 32,
                 eos_token_id=None, top_k: int = 0,
                 seed: int = 0, steps_per_tick: int = 1,
                 prefill_buckets: tuple[int, ...] | None = None,
                 draft_module=None, draft_variables=None, gamma: int = 4,
                 prefill_chunk: int = 0, paged_kv=None,
                 block_budget: bool = False, max_chunks_per_tick: int = 1,
                 tracer=None, tsdb=None):
        # tracer (tracing.Tracer): per-request spans — queue wait, one
        # span per prefill chunk (reused-vs-computed counts), decode
        # window, and a `request` root when no fleet owns one. tsdb
        # (monitoring.TimeSeriesStore): decode-tick and TTFT samples
        # for the SLO burn-rate monitor. Both default off at zero cost
        # on the tick path (docs/slo.md).
        self.tracer = tracer
        self.tsdb = tsdb
        cfg = module.cfg
        # chunked prefill (prefill_chunk > 0): long prompts admit in
        # fixed-token chunks interleaved with decode ticks — at most ONE
        # chunk of prefill work per tick, so a 4k-token prompt never
        # stalls in-flight decode rows more than one chunk budget. The
        # per-row block-write path (models/gpt.py vmapped
        # dynamic_update_slice at each row's cache_index) makes the
        # chunked cache identical to a one-shot prefill's, so the first
        # token — and every token after it — is token-identical.
        # paged_kv (fleet.PagedKVPool): the pool is the KV substrate for
        # the WHOLE row lifetime — the matched prefix K/V seeds the row
        # cache at admission (only the suffix runs through the model),
        # and every decode dispatch appends its freshly-written K/V to
        # the row's block chain (docs/serving.md). block_budget=True
        # additionally gates admission on the pool's free-block count
        # (prompt + budget blocks must fit the working set) instead of
        # row slots alone. max_chunks_per_tick lifts the one-chunk
        # stall bound for PURE-PREFILL replicas (the disaggregated
        # tier's prefill role has no decode rows to starve).
        self.prefill_chunk = int(prefill_chunk)
        self.paged_kv = paged_kv
        self.block_budget = bool(block_budget) and paged_kv is not None
        self.max_chunks_per_tick = int(max_chunks_per_tick)
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}")
        if self.max_chunks_per_tick < 1:
            raise ValueError(
                f"max_chunks_per_tick must be >= 1, got "
                f"{max_chunks_per_tick}")
        if self.prefill_chunk or paged_kv is not None:
            what = ("prefill_chunk" if self.prefill_chunk else "paged_kv")
            if prefill_buckets is not None:
                raise ValueError(
                    f"{what} replaces bucketed prefill — the chunk walk "
                    "already bounds the executable count; configure one")
            if getattr(cfg, "kv_cache_capacity", 0):
                raise ValueError(
                    f"{what} requires the full KV cache: ring-slot "
                    "identity is ambiguous for seeded/partial prefixes")
        # MoE models are row-independent at decode since the decode path
        # routes DROPLESS (parallel/moe.py, VERDICT r4 #6): no capacity,
        # no cross-row drop coupling — so the engine serves them exactly.
        # Speculative mode (VERDICT r4 #5): a draft model proposes gamma
        # tokens per row, the target verifies all rows' proposals in ONE
        # (R, gamma+1) pass, and each row rewinds to ITS accepted length —
        # the solo speculative rewind applied rowwise via the per-row
        # cache_index vectors. Greedy rows stay EXACTLY the target's
        # greedy decode (acceptance is argmax-match), so mixing row depths
        # changes nothing. One spec round per tick, all inside one
        # executable (draft scan + verify fused).
        self.draft_module = draft_module
        self.draft_variables = draft_variables
        self.gamma = int(gamma)
        if draft_module is not None:
            for m, name in ((module, "target"), (draft_module, "draft")):
                if getattr(m.cfg, "kv_cache_capacity", 0):
                    raise ValueError(
                        f"{name} uses a rolling KV cache — speculative "
                        "rewind makes ring-slot identity ambiguous")
            if draft_module.cfg.vocab_size != cfg.vocab_size:
                raise ValueError("draft must share the target vocabulary")
            if prefill_buckets is not None:
                raise ValueError(
                    "speculative engine does not support prefill_buckets "
                    "yet: the draft prefill would need the same pad-rewind")
            if steps_per_tick != 1:
                raise ValueError(
                    "speculative engine runs one spec round per tick "
                    "(gamma amortizes the dispatch); steps_per_tick must "
                    "be 1")
            if self.gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
        self.module = module
        self.variables = variables
        self.max_rows = int(max_rows)
        self.max_len = int(cfg.max_len)
        # rolling-cache models bound the prefill length (models/gpt.py
        # capacity law); validate at submit() so a too-long prompt is the
        # CALLER's error, not a trace-time exception on the engine thread
        cap = int(getattr(cfg, "kv_cache_capacity", 0) or 0)
        self.max_prompt_len = (
            cap - int(cfg.attention_window) + 1 if cap else self.max_len)
        # bucketed prefill: pad prompts right to the smallest bucket and
        # rewind the per-row index to the true length inside the jitted
        # prefill — ONE executable per bucket instead of one per distinct
        # prompt length (unbounded compile cache in production). The
        # stale pad rows are invisible under the full cache's position
        # mask; a ROLLING cache cannot tell stale newer writes from valid
        # older ones (same hazard as speculative rewind), so buckets are
        # refused there.
        if prefill_buckets is not None:
            if cap:
                raise ValueError(
                    "prefill_buckets requires the full KV cache: the pad "
                    "rewind makes rolling ring-slot identity ambiguous")
            buckets = tuple(sorted(int(x) for x in prefill_buckets))
            if not buckets or buckets[0] < 1:
                raise ValueError(f"bad prefill_buckets {prefill_buckets}")
            self.prefill_buckets = buckets
        else:
            self.prefill_buckets = None
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.eos_token_id = _eos_tuple(eos_token_id)
        self.top_k = int(top_k)  # static: one decode executable
        # decode steps per dispatch: scheduling stays iteration-level at
        # granularity T, but T tokens amortize one host round-trip — the
        # lever for dispatch-floored links (the axon tunnel's ~14 ms/step
        # would otherwise cap aggregate throughput at rows/14ms regardless
        # of chip speed). Rows retiring mid-scan just discard their tail.
        self.steps_per_tick = max(1, int(steps_per_tick))
        self._seed = int(seed)
        self._submitted = 0
        self._lock = make_lock("continuous.ContinuousBatcher._lock")
        self._queue: list[tuple[np.ndarray, _InFlight]] = []
        self._rows: list[_InFlight | None] = [None] * self.max_rows
        self._toks = np.zeros((self.max_rows,), np.int32)
        self._prefill_cache: dict[int, object] = {}  # prompt_len -> jitted
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.step_count = 0  # decode dispatches (the scheduling metric)

        # live batch cache: created by one R-row dummy decode step
        _, cache = module.apply(
            variables, jnp.zeros((self.max_rows, 1), jnp.int32),
            decode=True, mutable=["cache"])
        self._cache = cache["cache"]
        # chunked/seeded admission state: slot -> in-progress prefill;
        # ticker-private like _rows. _row_chains holds each DECODING
        # row's pool block chain (SequenceChain) — the pool-side twin of
        # the row's cache slice, grown per dispatch, released at retire
        # (or transferred to the handle on keep_chain/kill).
        self._pending: dict[int, _PendingPrefill] = {}
        self._row_chains: dict[int, object] = {}
        self._chunk_order: list[int] = []  # FIFO of pending slots
        self._chunk_fns: dict[int, object] = {}  # suffix len -> jitted
        self._draft_chunk_fns: dict[int, object] = {}
        self._row_template = None  # lazy batch-1 np zero cache twin
        self._draft_row_template = None
        # per-row cache depth (prompt + cache-written decode positions):
        # host-side truth like _toks — the spec step's rewind base AND
        # the paged chain-append's extraction start
        self._depths = np.zeros((self.max_rows,), np.int32)
        #: prefill-unit accounting (the prefix-reuse proof reads these):
        #: tokens the model actually computed vs tokens seeded for free
        self.prefill_tokens_total = 0
        self.prefill_tokens_reused = 0
        if draft_module is not None:
            _, dcache = draft_module.apply(
                draft_variables, jnp.zeros((self.max_rows, 1), jnp.int32),
                decode=True, mutable=["cache"])
            self._dcache = dcache["cache"]
            self._draft_prefill_cache: dict[int, object] = {}

        def _splice(big, row, i):
            """Write batch-1 row-cache `row` into slot i of the live
            cache — every leaf's leading dim is the row dim."""
            def leaf(b, r):
                return jax.lax.dynamic_update_slice(
                    b, r.astype(b.dtype), (i,) + (0,) * (b.ndim - 1))
            return jax.tree.map(leaf, big, row)

        self._splice = jax.jit(_splice)
        top_k_ = self.top_k

        def _pick(logits, temps, keys):
            """Per-row next token: argmax where temperature == 0, else a
            categorical draw with that row's key (top_k is engine-static
            so everything stays one executable)."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            if top_k_ > 0:
                kth = jax.lax.top_k(scaled, top_k_)[0][..., -1:]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            sampled = jax.vmap(jax.random.categorical)(
                keys, scaled).astype(jnp.int32)
            return jnp.where(temps > 0, sampled, greedy)

        T = self.steps_per_tick
        paged = paged_kv is not None

        def _one(cache_col, toks, active, temps, keys):
            from kubeflow_tpu.models.gpt import set_cache_indices

            logits, new_cache = module.apply(
                {**variables, "cache": cache_col},
                toks[:, None], decode=True, mutable=["cache"])
            nxt = _pick(logits[:, 0].astype(jnp.float32), temps, keys)
            # free rows keep decoding garbage (their slot is overwritten
            # wholesale on admission) — but their index must not creep past
            # max_len, so park it at 0
            return nxt, set_cache_indices(new_cache["cache"], active=active)

        def _step(cache_col, toks, active, temps, base_keys, starts,
                  depths):
            """T chained decode steps in ONE dispatch; returns the (T, R)
            emitted tokens. Rows that retire mid-scan decode on — their
            tail is discarded on the host (iteration-level scheduling at
            granularity T). With a paged pool the dispatch ALSO gathers
            the freshly-written K/V window [depths, depths+T) per row
            (models/gpt.gather_kv_rows) — the chain-append extraction
            rides the step executable instead of costing a second
            dispatch on the tick path."""
            def body(carry, j):
                cache_col, toks = carry
                keys = jax.vmap(jax.random.fold_in)(base_keys, starts + j)
                nxt, cache_col = _one(cache_col, toks, active, temps, keys)
                return (cache_col, nxt), nxt

            (cache_col, _), out = jax.lax.scan(
                body, (cache_col, toks), jnp.arange(T))
            if paged:
                from kubeflow_tpu.models.gpt import gather_kv_rows

                return out, cache_col, gather_kv_rows(cache_col, depths, T)
            return out, cache_col

        self._step = jax.jit(_step)

        if draft_module is not None:
            G = self.gamma
            from kubeflow_tpu.models.gpt import set_cache_indices

            # per-row index rewrite shared with models/gpt.py (one owner
            # of the cache-index contract); inactive rows park at 0
            def _set_row_indices(cache, values, active):
                return set_cache_indices(cache, values, active)

            def _spec_step(t_cache, d_cache, toks, active, depths, temps,
                           base_keys, any_sampled):
                """One speculative round for ALL rows in one dispatch:
                draft proposes G tokens/row (G chained batch-R steps),
                target verifies (R, G+1) in one pass, each row accepts
                its own prefix and rewinds to its own depth. Greedy rows
                (temp == 0) accept on argmax-match; sampled rows run the
                Leviathan/Chen rejection per row — accept with
                min(1, p_t/p_d), residual resample at the first
                rejection, bonus token from p_t (the solo
                models/speculative.py scheme applied rowwise; greedy and
                sampled rows mix in ONE executable via where(temps>0)).
                Per-(row, round, step) keys fold the request key with
                depth*(G+3)+j — depth strictly increases per round, so
                keys never repeat. Returns the (R, G+1) emission buffer
                and per-row accept counts.

                `any_sampled` is STATIC (jit retraces when the greedy/
                sampled mix changes, exactly like prefill buckets
                retrace per bucket): an all-greedy batch specializes to
                the cheap executable — no (R, G+1, V) softmaxes, no
                per-draft-step categorical draws, no residual clip/
                normalize/resample — so greedy-only speculative
                deployments keep paying only argmax (ADVICE r5).
                Greedy rows' tokens are IDENTICAL either way: the mixed
                executable computes the sampling machinery and discards
                it rowwise via where(temps>0); the specialized one just
                never computes it (pinned by test_continuous)."""
                t_cache = _set_row_indices(t_cache, depths, active)
                d_cache = _set_row_indices(d_cache, depths, active)
                tp = jnp.maximum(temps, 1e-6)[:, None]       # (R, 1)
                key_base = depths * (G + 3)

                def draft_step(carry, j):
                    cache, tok = carry
                    logits, new = draft_module.apply(
                        {**draft_variables, "cache": cache}, tok[:, None],
                        decode=True, mutable=["cache"])
                    row = logits[:, -1].astype(jnp.float32)  # (R, V)
                    greedy = jnp.argmax(row, axis=-1).astype(jnp.int32)
                    if not any_sampled:
                        return (new["cache"], greedy), greedy
                    keys = jax.vmap(jax.random.fold_in)(
                        base_keys, key_base + j)
                    sampled = jax.vmap(jax.random.categorical)(
                        keys, row / tp).astype(jnp.int32)
                    nxt = jnp.where(temps > 0, sampled, greedy)
                    probs = jax.nn.softmax(row / tp, axis=-1)
                    return (new["cache"], nxt), (nxt, probs)

                (d_cache, p_last), ys = jax.lax.scan(
                    draft_step, (d_cache, toks), jnp.arange(G))
                if any_sampled:
                    props, d_probs = ys
                    d_probs = d_probs.transpose(1, 0, 2)     # (R, G, V)
                else:
                    props = ys
                props = props.T                              # (R, G)
                # extra draft write (solo speculative does the same) so an
                # all-accepted round leaves no unwritten draft row
                (d_cache, _), _ = draft_step((d_cache, p_last),
                                             jnp.int32(G + 2))
                inp = jnp.concatenate([toks[:, None], props], axis=1)
                logits, t_adv = module.apply(
                    {**variables, "cache": t_cache}, inp,
                    decode=True, mutable=["cache"])
                t_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                # --- acceptance: argmax-match (greedy) | rejection ----
                ok_greedy = props == t_tokens[:, :G]
                if any_sampled:
                    p_t = jax.nn.softmax(
                        logits.astype(jnp.float32) / tp[..., None], axis=-1
                    )                                        # (R, G+1, V)
                    pt_x = jnp.take_along_axis(
                        p_t[:, :G], props[..., None], axis=-1)[..., 0]
                    pd_x = jnp.take_along_axis(
                        d_probs, props[..., None], axis=-1)[..., 0]
                    u_keys = jax.vmap(jax.random.fold_in)(
                        base_keys, key_base + G)
                    u = jax.vmap(
                        lambda k: jax.random.uniform(k, (G,)))(u_keys)
                    ok_sampled = u < jnp.minimum(
                        1.0, pt_x / jnp.maximum(pd_x, 1e-30))
                    ok = jnp.where(temps[:, None] > 0, ok_sampled,
                                   ok_greedy)
                else:
                    ok = ok_greedy
                agree = jnp.cumprod(ok.astype(jnp.int32), axis=1)
                a = agree.sum(axis=1)                        # (R,)
                # --- correction token ---------------------------------
                corr_greedy = jnp.take_along_axis(
                    t_tokens, a[:, None], axis=1)
                if any_sampled:
                    residual = jnp.clip(p_t[:, :G] - d_probs, 0.0)
                    rs = residual.sum(-1, keepdims=True)
                    res_norm = jnp.where(
                        rs > 0, residual / jnp.maximum(rs, 1e-30),
                        p_t[:, :G])
                    corr_rows = jnp.concatenate(
                        [res_norm, p_t[:, G:]], axis=1)      # (R, G+1, V)
                    picked = jnp.take_along_axis(
                        corr_rows, a[:, None, None], axis=1)[:, 0]
                    c_keys = jax.vmap(jax.random.fold_in)(
                        base_keys, key_base + G + 1)
                    corr_sampled = jax.vmap(jax.random.categorical)(
                        c_keys, jnp.log(jnp.maximum(picked, 1e-30))
                    ).astype(jnp.int32)[:, None]
                    corr = jnp.where(temps[:, None] > 0, corr_sampled,
                                     corr_greedy)
                else:
                    corr = corr_greedy
                padded = jnp.concatenate(
                    [props, jnp.zeros((props.shape[0], 1), jnp.int32)],
                    axis=1)
                upd = jnp.where(
                    jnp.arange(G + 1)[None, :] < a[:, None], padded, corr)
                new_depths = depths + a + 1
                t_cache = _set_row_indices(
                    t_adv["cache"], new_depths, active)
                d_cache = _set_row_indices(d_cache, new_depths, active)
                if paged:
                    from kubeflow_tpu.models.gpt import gather_kv_rows

                    win = gather_kv_rows(t_cache, depths, G + 1)
                    return upd, a, t_cache, d_cache, win
                return upd, a, t_cache, d_cache

            self._spec_step = jax.jit(_spec_step, static_argnums=(7,))

        def _pick_first(logits, temp, key):
            return _pick(logits[None].astype(jnp.float32),
                         jnp.asarray([temp], jnp.float32), key[None])[0]

        self._pick_first = jax.jit(_pick_first)

    # ---------------------------------------------------------------- API

    def submit(self, prompt_ids, max_new_tokens: int | None = None,
               eos_token_id=None, temperature: float = 0.0,
               key=None, on_token=None, on_done=None,
               trace_ctx=None, request_id: str = "",
               keep_chain: bool = False, resume_from=None) -> _InFlight:
        # keep_chain: retire transfers the row's paged block chain to the
        # handle (handle.chain) instead of releasing it — the
        # disaggregated prefill replica's publish side. resume_from =
        # (SequenceChain, tokens): seat the row by SEEDING its cache from
        # the chain (no prefill compute) with `tokens` already emitted —
        # the decode replica's adopt side AND the kill-requeue resume;
        # max_new_tokens still bounds the TOTAL tokens, resumed included.
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        budget = int(max_new_tokens or self.default_max_new_tokens)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if resume_from is not None:
            if self.paged_kv is None:
                raise ValueError("resume_from requires a paged_kv pool")
            chain, toks = resume_from
            if chain.frozen:
                raise ValueError("cannot resume from a frozen chain")
            if chain.pool is not self.paged_kv:
                raise ValueError(
                    "resume chain lives in a different pool than this "
                    "engine's")
            if not toks:
                raise ValueError("resume_from needs >= 1 emitted token")
            if chain.length != ids.size + len(toks) - 1:
                raise ValueError(
                    f"resume chain covers {chain.length} positions, "
                    f"expected prompt {ids.size} + {len(toks)} tokens "
                    f"- 1 = {ids.size + len(toks) - 1}")
            if len(toks) >= budget:
                raise ValueError(
                    "resume tokens already meet max_new_tokens")
        if self.block_budget and resume_from is None:
            import math

            need = math.ceil((ids.size + budget)
                             / self.paged_kv.block_size)
            if need > self.paged_kv.capacity_blocks:
                raise ValueError(
                    f"prompt {ids.size} + budget {budget} needs {need} "
                    f"KV blocks, beyond the pool's capacity "
                    f"{self.paged_kv.capacity_blocks}")
        if self.draft_module is not None:
            if temperature > 0 and self.top_k > 0:
                # greedy rows ignore top_k, so greedy-only deployments
                # with a configured top_k keep constructing/serving; the
                # refusal fires only where it matters — a SAMPLED row,
                # whose rejection scheme must accept against the draft's
                # ACTUAL proposal distribution (a top_k-truncated
                # p_d/p_t pair needs both sides renormalized
                # consistently; not implemented)
                raise ValueError(
                    "sampled rows in the speculative engine do not "
                    "compose with engine-level top_k")
            lim = min(self.max_len, self.draft_module.cfg.max_len)
            if ids.size + budget + self.gamma + 1 > lim:
                raise ValueError(
                    f"prompt {ids.size} + max_new_tokens {budget} + "
                    f"gamma+1 {self.gamma + 1} exceeds max_len {lim} "
                    "(a verify block may overshoot the budget)")
        elif ids.size + budget > self.max_len:
            raise ValueError(
                f"prompt {ids.size} + max_new_tokens {budget} exceeds "
                f"max_len {self.max_len}")
        if ids.size > self.max_prompt_len:
            raise ValueError(
                f"prompt {ids.size} exceeds the rolling cache's prefill "
                f"budget {self.max_prompt_len} (capacity - window + 1)")
        if (self.prefill_buckets is not None
                and ids.size > self.prefill_buckets[-1]):
            raise ValueError(
                f"prompt {ids.size} exceeds the largest prefill bucket "
                f"{self.prefill_buckets[-1]}")
        with self._lock:
            self._submitted += 1
            if key is None:
                # per-request key: engine seed folded with a monotonically
                # advancing submit counter (same contract as the sampling
                # predictor's per-request keys)
                key = jax.random.fold_in(
                    jax.random.PRNGKey(self._seed), self._submitted)
            req = _InFlight(slot=-1, max_new_tokens=budget,
                            eos_token_id=(self.eos_token_id
                                          if eos_token_id is None
                                          else _eos_tuple(eos_token_id)),
                            temperature=float(temperature), key=key,
                            t_submit=time.perf_counter(),
                            on_token=on_token, on_done=on_done)
            req.t_submit_wall = time.time()
            req._tsdb = self.tsdb
            req._keep_chain = bool(keep_chain)
            if resume_from is not None:
                chain, toks = resume_from
                req._resume = (chain, [int(t) for t in toks])
                req.resumed = True
            tr = armed_tracer(self.tracer)
            if tr is not None:
                req._tracer = tr
                req.request_id = request_id
                if trace_ctx is not None:
                    # the fleet router owns the `request` root span; the
                    # engine only contributes phase spans under it
                    req.trace_ctx = trace_ctx
                else:
                    req.own_root = True
                    req.parent_ctx = current_context()
                    req.trace_ctx = tr.allocate_context(
                        parent=req.parent_ctx)
                    if not req.request_id:
                        from kubeflow_tpu.serving.requestid import (
                            get_request_id,
                        )

                        req.request_id = get_request_id()
            self._queue.append((ids, req))
        return req

    def _prefill(self, ids: np.ndarray):
        if self.prefill_buckets is None:
            fn = self._prefill_cache.get(ids.size)
            if fn is None:
                def prefill(x):
                    logits, cache = self.module.apply(
                        self.variables, x, decode=True, mutable=["cache"])
                    return logits[:, -1], cache["cache"]
                fn = self._prefill_cache[ids.size] = jax.jit(prefill)
            return fn(ids[None, :])
        # bucketed: pad right, take logits at the TRUE last position, and
        # rewind cache_index/pos_index to the true length — pad rows stay
        # invisible under the position mask
        bucket = next((b for b in self.prefill_buckets if b >= ids.size),
                      None)
        if bucket is None:
            raise ValueError(
                f"prompt {ids.size} exceeds the largest prefill bucket "
                f"{self.prefill_buckets[-1]}")
        fn = self._prefill_cache.get(bucket)
        if fn is None:
            def prefill(x, true_len):
                logits, cache = self.module.apply(
                    self.variables, x, decode=True, mutable=["cache"])
                last = jax.lax.dynamic_index_in_dim(
                    logits, true_len - 1, axis=1, keepdims=False)

                def rewind(path, leaf):
                    name = getattr(path[-1], "key", "")
                    if name in ("cache_index", "pos_index"):
                        return jnp.full_like(leaf, true_len)
                    return leaf

                return last, jax.tree_util.tree_map_with_path(
                    rewind, cache["cache"])
            fn = self._prefill_cache[bucket] = jax.jit(prefill)
        padded = np.zeros((bucket,), np.int32)
        padded[:ids.size] = ids
        return fn(padded[None, :], jnp.int32(ids.size))

    def _draft_prefill(self, ids: np.ndarray):
        fn = self._draft_prefill_cache.get(ids.size)
        if fn is None:
            def prefill(x):
                _, cache = self.draft_module.apply(
                    self.draft_variables, x, decode=True, mutable=["cache"])
                return cache["cache"]
            fn = self._draft_prefill_cache[ids.size] = jax.jit(prefill)
        return fn(ids[None, :])

    # -------------------------------------------- chunked/seeded prefill

    def _apply_chunk(self, cache, chunk: np.ndarray):
        """One prefill chunk through the model on a batch-1 row cache:
        (last-position logits, advanced cache). Jitted per chunk length —
        with prefill_chunk set the executable count is bounded by
        chunk + remainder lengths, the production compile-cache story
        bucketed prefill approximated."""
        fn = self._chunk_fns.get(chunk.size)
        if fn is None:
            def apply(cache, x):
                logits, new = self.module.apply(
                    {**self.variables, "cache": cache}, x,
                    decode=True, mutable=["cache"])
                return logits[:, -1], new["cache"]
            fn = self._chunk_fns[chunk.size] = jax.jit(apply)
        return fn(cache, chunk[None, :])

    def _row_cache_template(self):
        from kubeflow_tpu.serving.fleet.pagedkv import make_row_template

        if self._row_template is None:
            self._row_template = make_row_template(self._cache)
        return self._row_template

    def _draft_row_cache_template(self):
        from kubeflow_tpu.serving.fleet.pagedkv import make_row_template

        if self._draft_row_template is None:
            self._draft_row_template = make_row_template(self._dcache)
        return self._draft_row_template

    def _begin_prefill(self, slot: int, ids: np.ndarray,
                       req: _InFlight) -> None:
        """Seat a row on the chunked/seeded admission path: reuse any
        pooled prefix, then either finish the suffix now (prefill_chunk
        == 0) or leave the row pending for chunk-per-tick advancement.
        With a draft model the draft's batch-1 cache prefills over the
        SAME chunk schedule (the pool stores only target K/V, so the
        draft computes every position — it only shapes acceptance
        speed, never the emitted tokens)."""
        from kubeflow_tpu.serving.fleet.pagedkv import seed_row_cache

        template = self._row_cache_template()
        cache = None
        pos, refs = 0, []
        if self.paged_kv is not None:
            m = self.paged_kv.match(ids)
            # at least one position must run through the model — the row
            # needs the last position's logits to pick its first token
            pos = min(m.length, ids.size - 1)
            if pos > 0:
                # seed_row_cache copies every leaf itself — seeding from
                # the template directly spares the hot reuse path a whole
                # wasted row-cache memcpy per admission
                cache = seed_row_cache(template, m.kv, pos)
                refs = m.blocks
                self.prefill_tokens_reused += pos
            elif m.blocks:
                self.paged_kv.release(m.blocks)
        if cache is None:
            # leaves are np arrays: fresh copy per admission
            cache = jax.tree.map(np.copy, template)
        if pos > 0 and req._tracer is not None:
            # the prefix-reuse ledger's trace form: these positions were
            # seeded from the paged pool, never computed
            req._tracer.event("engine.prefill_seed", parent=req.trace_ctx,
                              tokens_reused=pos)
        pend = _PendingPrefill(req=req, ids=ids, pos=pos, cache=cache,
                               match_refs=refs)
        if self.draft_module is not None:
            pend.d_cache = jax.tree.map(np.copy,
                                        self._draft_row_cache_template())
        self._pending[slot] = pend
        self._chunk_order.append(slot)
        if not self.prefill_chunk:
            while slot in self._pending:  # suffix in one pass
                self._advance_prefill(slot)

    def _admit_resume(self, slot: int, ids: np.ndarray,
                      req: _InFlight) -> None:
        """Seat a row by RESUMING its paged chain: the pool's gathered
        K/V seeds the whole cache (zero prefill compute — the
        disaggregated handoff / kill-requeue admission), the emitted
        tokens are pre-fed without re-firing callbacks, and decode
        continues from the chain's end. With a draft model the draft
        cache still prefills (chunked) over the known token history —
        draft state isn't pooled, but it never changes emitted tokens."""
        from kubeflow_tpu.serving.fleet.pagedkv import seed_row_cache

        chain, toks = req._resume
        full_ids = (np.concatenate([ids, np.asarray(toks[:-1], np.int32)])
                    if len(toks) > 1 else ids)
        _, kv = self.paged_kv.gather(chain.refs)
        cache = seed_row_cache(self._row_cache_template(), kv,
                               chain.length)
        req.tokens = list(toks)      # pre-fed: callbacks never re-fire
        req.t_first = time.perf_counter()   # the resume point, not TTFT
        req.t_first_wall = time.time()
        if req._tracer is not None:
            req._tracer.event(
                "engine.resume", parent=req.trace_ctx,
                resumed_positions=int(chain.length),
                tokens_resumed=len(toks), slot=slot)
        if self.draft_module is not None:
            pend = _PendingPrefill(req=req, ids=full_ids,
                                   pos=len(full_ids), cache=cache,
                                   resume=True)
            pend.d_cache = jax.tree.map(
                np.copy, self._draft_row_cache_template())
            self._pending[slot] = pend
            self._chunk_order.append(slot)
            self._row_chains[slot] = chain
            req._resume = None
            if not self.prefill_chunk:
                while slot in self._pending:
                    self._advance_prefill(slot)
            return
        self._cache = self._splice(self._cache, cache, jnp.int32(slot))
        self._toks[slot] = int(toks[-1])
        self._depths[slot] = chain.length
        self._row_chains[slot] = chain
        req._resume = None

    def _apply_draft_chunk(self, cache, chunk: np.ndarray):
        """One draft-prefill chunk on a batch-1 draft row cache (cache
        only — the draft's logits are never needed at admission)."""
        fn = self._draft_chunk_fns.get(chunk.size)
        if fn is None:
            def apply(cache, x):
                _, new = self.draft_module.apply(
                    {**self.draft_variables, "cache": cache}, x,
                    decode=True, mutable=["cache"])
                return new["cache"]
            fn = self._draft_chunk_fns[chunk.size] = jax.jit(apply)
        return fn(cache, chunk[None, :])

    def _advance_prefill(self, slot: int) -> None:
        """Run ONE chunk unit (or the whole remaining work when chunking
        is off) of a pending row: a target chunk while the prompt suffix
        remains, plus a draft chunk while the draft cache lags; completes
        admission when both are done."""
        pend = self._pending[slot]
        whole = not self.prefill_chunk
        if pend.pos < len(pend.ids):
            take = (len(pend.ids) - pend.pos if whole
                    else min(self.prefill_chunk, len(pend.ids) - pend.pos))
            chunk = pend.ids[pend.pos:pend.pos + take]
            # the FIRST computed chunk (no logits yet) carries the seeded
            # reuse count — reused-vs-computed per chunk off the ledger
            reused = pend.pos if pend.last_logits is None else 0
            w0, p0 = time.time(), time.perf_counter()
            pend.last_logits, pend.cache = self._apply_chunk(pend.cache,
                                                             chunk)
            if pend.req._tracer is not None:
                pend.req._tracer.record_span(
                    "engine.prefill_chunk", w0, time.perf_counter() - p0,
                    parent=pend.req.trace_ctx, tokens_computed=take,
                    tokens_reused=reused, pos=pend.pos + take)
            pend.pos += take
            self.prefill_tokens_total += take
        if pend.d_cache is not None and pend.d_pos < len(pend.ids):
            take = (len(pend.ids) - pend.d_pos if whole
                    else min(self.prefill_chunk,
                             len(pend.ids) - pend.d_pos))
            chunk = pend.ids[pend.d_pos:pend.d_pos + take]
            w0, p0 = time.time(), time.perf_counter()
            pend.d_cache = self._apply_draft_chunk(pend.d_cache, chunk)
            if pend.req._tracer is not None:
                # distinct name: request_breakdown charges it to the
                # prefill phase but its tokens never enter the
                # reused-vs-computed prompt ledger (draft work is
                # acceptance fuel, not prompt prefill)
                pend.req._tracer.record_span(
                    "engine.draft_prefill_chunk", w0,
                    time.perf_counter() - p0, parent=pend.req.trace_ctx,
                    tokens_computed=take, pos=pend.d_pos + take)
            pend.d_pos += take
        if pend.pos >= len(pend.ids) and (
                pend.d_cache is None or pend.d_pos >= len(pend.ids)):
            self._finish_prefill(slot)

    def _finish_prefill(self, slot: int) -> None:
        """Admission completes: publish the prompt's K/V to the paged
        pool (becoming the row's lifetime block chain), splice the row
        cache into the live batch, emit the first token. Resume rows
        skip publish and first-token — their chain and tokens already
        exist."""
        pend = self._pending.pop(slot)
        self._chunk_order.remove(slot)
        req = pend.req
        if pend.resume:
            # chain already held in _row_chains; tokens pre-fed
            self._cache = self._splice(
                self._cache, pend.cache, jnp.int32(slot))
            if pend.d_cache is not None:
                self._dcache = self._splice(
                    self._dcache, pend.d_cache, jnp.int32(slot))
            self._toks[slot] = int(req.tokens[-1])
            self._depths[slot] = len(pend.ids)
            return
        if self.paged_kv is not None:
            from kubeflow_tpu.serving.fleet.pagedkv import (
                SequenceChain,
                extract_prompt_kv,
            )

            kv = extract_prompt_kv(pend.cache, len(pend.ids))
            held = self.paged_kv.insert(pend.ids, kv)
            # insert's refs cover (and extend) the admission match's
            self.paged_kv.release(pend.match_refs)
            # expect_length marks chains that could not cover the whole
            # prompt (insert stopped at a covered-by-sibling boundary)
            # as frozen: release-only, never appended or resumed
            self._row_chains[slot] = SequenceChain(
                self.paged_kv, held, expect_length=len(pend.ids))
        self._cache = self._splice(
            self._cache, pend.cache, jnp.int32(slot))
        if pend.d_cache is not None:
            self._dcache = self._splice(
                self._dcache, pend.d_cache, jnp.int32(slot))
        self._depths[slot] = len(pend.ids)
        first = self._pick_first(
            pend.last_logits[0], req.temperature,
            jax.random.fold_in(req.key, 0))
        req.push(int(first))
        self._toks[slot] = int(first)
        if self._finished(req):
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        req = self._rows[slot]
        self._rows[slot] = None
        chain = self._row_chains.pop(slot, None)
        if chain is not None:
            if req._keep_chain:
                # ownership to the handle's consumer — the disaggregated
                # router adopts the chain for the decode tier
                req.chain = chain
            else:
                chain.release()
        req.finish()

    def _blocks_fit(self, ids: np.ndarray, req: _InFlight) -> bool:
        """Block-budgeted admission check: does the pool's free-block
        count cover this request's worst-case growth (prompt + budget;
        a resume chain already pins its blocks, so only the remaining
        budget counts)? Conservative — prefix reuse can only need
        less."""
        import math

        bs = self.paged_kv.block_size
        if req._resume is not None:
            chain, _ = req._resume
            need = math.ceil(max(
                ids.size + req.max_new_tokens - chain.length, 0) / bs)
        else:
            need = math.ceil((ids.size + req.max_new_tokens) / bs)
        return self.paged_kv.available_blocks() >= need

    def _append_decode_kv(self, win, active: np.ndarray,
                          window: int, counts=None) -> None:
        """Grow each alive row's pool block chain with the positions the
        decode dispatch just wrote: the paged pool stays the KV substrate
        for the WHOLE lifetime, so a killed replica's rows can resume
        from their surviving chains and follow-on turns match into the
        generated suffix. `win` is the gathered per-row K/V window the
        step dispatch itself returned (the extraction rides the decode
        executable — no second dispatch). Rows that retired mid-dispatch
        already released their chain; frozen chains never grow."""
        rows = [slot for slot in range(self.max_rows)
                if active[slot] and self._rows[slot] is not None
                and slot in self._row_chains
                and not self._row_chains[slot].frozen]
        if not rows:
            return
        win = jax.device_get(win)
        for slot in rows:
            n = window if counts is None else int(counts[slot])
            req = self._rows[slot]
            k = len(req.tokens)
            # position p holds the KV of sequence token p; the window
            # [d, d+n) maps to emitted tokens [k-n-1, k-1) (the dispatch
            # INPUTS — the newest token's KV lands next dispatch)
            ids_seg = req.tokens[k - n - 1:k - 1]
            self._row_chains[slot].append(
                ids_seg, {p: a[slot, :n] for p, a in win.items()})

    def tick(self) -> bool:
        """One scheduling round: admit queued prompts into free rows, then
        advance every in-flight row steps_per_tick tokens. Returns True if
        any work remains.

        Locking: tick() is single-ticker by contract (run_until_idle OR
        the serving thread); rows/cache/toks are ticker-private. The lock
        guards ONLY the shared queue, so submit() from request threads
        never waits behind device dispatches."""
        # ---- admission: prefill into free rows ---------------------------
        chunked = self.prefill_chunk > 0 or self.paged_kv is not None
        for slot in range(self.max_rows):
            if self._rows[slot] is not None:
                continue
            with self._lock:
                if not self._queue:
                    break
                if self.block_budget \
                        and not self._blocks_fit(*self._queue[0]):
                    # block-budgeted admission: the pool's free-block
                    # count, not the row slot, is the admission token —
                    # FIFO preserved (head waits, nothing jumps it)
                    break
                ids, req = self._queue.pop(0)
            # seat the row BEFORE device work: a prefill failure must find
            # the request in _rows so _fail_all unblocks its caller
            req.slot = slot
            self._rows[slot] = req
            if req._tracer is not None:
                req._tracer.record_span(
                    "engine.queue_wait", req.t_submit_wall,
                    time.perf_counter() - req.t_submit,
                    parent=req.trace_ctx, slot=slot)
            if req._resume is not None:
                # resume admission: seed the whole cache from the paged
                # chain — zero prefill compute, decode continues
                self._admit_resume(slot, ids, req)
                continue
            if chunked:
                # chunked/seeded path: pooled prefix reuse + (with
                # prefill_chunk) chunk-per-tick interleaving below
                self._begin_prefill(slot, ids, req)
                continue
            w0, p0 = time.time(), time.perf_counter()
            last_logits, row_cache = self._prefill(ids)
            if req._tracer is not None:
                req._tracer.record_span(
                    "engine.prefill_chunk", w0, time.perf_counter() - p0,
                    parent=req.trace_ctx, tokens_computed=ids.size,
                    tokens_reused=0)
            self.prefill_tokens_total += ids.size
            self._cache = self._splice(
                self._cache, row_cache, jnp.int32(slot))
            if self.draft_module is not None:
                self._dcache = self._splice(
                    self._dcache, self._draft_prefill(ids), jnp.int32(slot))
            self._depths[slot] = ids.size
            first = self._pick_first(
                last_logits[0], req.temperature,
                jax.random.fold_in(req.key, 0))
            req.push(int(first))
            self._toks[slot] = int(first)
            # the prefill's first token may already finish the row
            if self._finished(req):
                self._retire(slot)
        # ---- chunked prefill: one chunk unit per tick (FIFO over pending
        # rows) so admission work interleaves with — never starves — the
        # decode dispatch below (the one-chunk-budget stall bound). A
        # pure-prefill replica (the disaggregated tier) raises
        # max_chunks_per_tick: it has no decode rows to starve.
        chunks = self.max_chunks_per_tick
        while self._chunk_order and chunks > 0:
            self._advance_prefill(self._chunk_order[0])
            chunks -= 1
        active = np.array(
            [r is not None and s not in self._pending
             for s, r in enumerate(self._rows)])
        if not active.any():
            with self._lock:
                return bool(self._queue) or bool(self._pending)
        if self.draft_module is not None:
            return self._spec_tick(active)
        # ---- T decode steps for every in-flight row ----------------------
        temps, base_keys = self._row_sampling_state()
        starts = np.array(
            [len(r.tokens) if r is not None else 0
             for r in self._rows], np.int32)
        depths0 = self._depths.copy()  # pre-dispatch: the append window
        # one read per tick: start_slo's live-attach assigns self.tsdb
        # from another thread, and a torn double-read would record an
        # absolute perf_counter value as a decode-tick sample
        tsdb = self.tsdb
        t_dec = time.perf_counter() if tsdb is not None else 0.0
        res = self._step(
            self._cache, jnp.asarray(self._toks),
            jnp.asarray(active), jnp.asarray(temps), base_keys,
            jnp.asarray(starts), jnp.asarray(depths0))
        win = None
        if self.paged_kv is not None:
            out, self._cache, win = res
        else:
            out, self._cache = res
        self.step_count += 1  # dispatches (the scheduling metric)
        out = np.asarray(out)  # (T, R)
        if tsdb is not None:
            # the decode-tick SLO series (docs/slo.md): one sample per
            # dispatch, measured to the host-visible sync (np.asarray).
            # Cost is one perf_counter read + a deque append — the
            # decode_tick perf gate runs WITH this live and keeps its
            # budget (tests/test_prof_gate.py), which is the off-the-
            # hot-path claim in falsifiable form
            tsdb.record("serving.decode_tick_s",
                        time.perf_counter() - t_dec)
        for slot, req in enumerate(self._rows):
            if req is None or slot in self._pending:
                continue  # pending rows decoded garbage; discard
            for j in range(out.shape[0]):
                req.push(int(out[j, slot]))
                self._toks[slot] = int(out[j, slot])
                if self._finished(req):
                    self._retire(slot)  # discard the scan tail
                    break
        if self.paged_kv is not None:
            self._append_decode_kv(win, active, out.shape[0])
        self._depths[active] += out.shape[0]
        with self._lock:
            pending = bool(self._queue)
        return pending or any(r is not None for r in self._rows)

    def _spec_tick(self, active: np.ndarray) -> bool:
        """One speculative round for every in-flight row (one dispatch):
        each row emits between 1 and gamma+1 tokens — its own accepted
        prefix plus the correction. Greedy rows are target-greedy-exact;
        sampled rows run the rowwise rejection scheme."""
        temps, base_keys = self._row_sampling_state()
        # STATIC any-sampled flag: an all-greedy batch dispatches the
        # specialized executable with no rejection-sampling machinery;
        # the first sampled admission retraces once (like a new prefill
        # bucket) and the mixed executable serves from then on
        tsdb = self.tsdb  # one read: live-attach races a torn pair
        t_dec = time.perf_counter() if tsdb is not None else 0.0
        res = self._spec_step(
            self._cache, self._dcache, jnp.asarray(self._toks),
            jnp.asarray(active), jnp.asarray(self._depths),
            jnp.asarray(temps), base_keys, bool((temps > 0).any()))
        win = None
        if self.paged_kv is not None:
            upd, a, self._cache, self._dcache, win = res
        else:
            upd, a, self._cache, self._dcache = res
        self.step_count += 1  # dispatches (the scheduling metric)
        upd = np.asarray(upd)                               # (R, gamma+1)
        a = np.asarray(a)                                   # (R,)
        if tsdb is not None:
            tsdb.record("serving.decode_tick_s",
                        time.perf_counter() - t_dec)
        for slot, req in enumerate(self._rows):
            if req is None or slot in self._pending:
                continue  # pending rows' round output is garbage
            self._depths[slot] += int(a[slot]) + 1
            for j in range(int(a[slot]) + 1):
                req.push(int(upd[slot, j]))
                self._toks[slot] = int(upd[slot, j])
                if self._finished(req):
                    self._retire(slot)  # discard the round's tail
                    break
        if self.paged_kv is not None:
            # each alive row accepted a+1 tokens: its verify pass wrote
            # valid K/V at [depth0, depth0 + a + 1) — append exactly that
            self._append_decode_kv(win, active, self.gamma + 1,
                                   counts=a + 1)
        with self._lock:
            pending = bool(self._queue)
        return pending or any(r is not None for r in self._rows)

    def _row_sampling_state(self):
        """(temps (R,) f32, base_keys (R, 2)) marshalled from the row
        table — the ONE definition both decode paths (plain tick and
        _spec_tick) feed their executables."""
        zero = jax.random.PRNGKey(0)
        temps = np.array(
            [r.temperature if r is not None else 0.0
             for r in self._rows], np.float32)
        base_keys = jnp.stack([
            r.key if r is not None and r.temperature > 0 else zero
            for r in self._rows])
        return temps, base_keys

    @staticmethod
    def _finished(req: _InFlight) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            return True
        return (req.eos_token_id is not None
                and req.tokens[-1] in req.eos_token_id)

    def run_until_idle(self) -> None:
        while self.tick():
            pass

    # ------------------------------------------------------- serving mode

    def start(self) -> "ContinuousBatcher":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                busy = self.tick()
            except Exception as exc:  # noqa: BLE001 — the engine must
                # survive a poisoned round: fail every request it was
                # carrying (their threads unblock with the error instead
                # of hanging to timeout) and keep serving fresh ones
                self._fail_all(f"{type(exc).__name__}: {exc}")
                busy = False
            if not busy:
                self._stop.wait(0.002)  # idle: poll the queue cheaply

    def _fail_all(self, reason: str) -> None:
        with self._lock:
            queued = [req for _, req in self._queue]
            self._queue.clear()

        def hand_off(req, chain) -> None:
            # a usable chain TRANSFERS to the handle only when the FLEET
            # ROUTER is listening (it wired this engine and its on_done
            # requeue resumes-or-releases every transferred chain — the
            # zero-redecode rescue); a direct consumer's on_done has no
            # such contract, so its chain releases and the blocks become
            # reuse inventory instead of leaking pins
            if chain is None:
                return
            if req is not None and req.on_done is not None \
                    and getattr(self, "_fleet_managed", False) \
                    and not chain.frozen:
                req.chain = chain
            else:
                chain.release()

        if self.paged_kv is not None:
            for pend in self._pending.values():
                self.paged_kv.release(pend.match_refs)
            for slot, chain in self._row_chains.items():
                hand_off(self._rows[slot], chain)
            for req in queued:
                if req._resume is not None:
                    chain, _ = req._resume
                    req._resume = None
                    hand_off(req, chain)
        self._pending.clear()
        self._chunk_order.clear()
        self._row_chains.clear()
        for req in queued + [r for r in self._rows if r is not None]:
            if req._resume is not None:
                # a seated-but-unqueued resume cannot exist; queued ones
                # were handled above — clear defensively
                req._resume = None
            req.finish(error=reason)
        self._rows = [None] * self.max_rows

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
