"""Activator — the serverless front door (Knative activator analogue).

Reference parity (unverified cites, SURVEY.md §2.5/§3.5): kserve's
serverless mode rides Knative, whose activator buffers requests for a
revision scaled to zero, pokes the autoscaler, and proxies once a pod is
up. The TPU rebuild keeps the platform semantics: ONE stable URL per
InferenceService (`/<namespace>/<name>/<v1|v2 path>`) that

  - round-robins ready predictor endpoints, honoring the canary traffic
    split (the istio VirtualService weight analogue),
  - at zero ready replicas stamps a demand annotation on the ISVC (the
    controller's scale-from-zero trigger), HOLDS the request through the
    cold start, and proxies when an endpoint appears — AOT-exported
    predictors make that window compile-free (serving/aot.py).
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.utils.retry import (
    BackoffPolicy,
    Deadline,
    backoff_sleep,
    poll_until,
)

#: annotation the activator stamps (epoch seconds) when a request arrives
#: for a scaled-to-zero service; the controller reads it as demand
DEMAND_ANNOTATION = "serving.kubeflow-tpu.org/activator-demand"

#: cold-start status polling: fast first checks (sub-second cold starts on
#: AOT-exported predictors answer immediately), jittered exponential ramp
#: to a gentle steady poll so a thundering herd of held requests doesn't
#: hammer the store in lockstep
COLD_START_POLL = BackoffPolicy(
    base_s=0.02, max_s=0.3, multiplier=2.0, jitter=0.5
)

#: proxy retry after a replica died between probe and proxy: bounded
#: attempts under the shared jittered policy, every sleep clamped to the
#: REQUEST deadline (the hand-rolled single retry this replaces could
#: neither back off nor take a second bite at a flapping fleet)
PROXY_RETRY = BackoffPolicy(
    base_s=0.02, max_s=0.5, multiplier=2.0, jitter=0.5, max_attempts=3
)

#: EWMA weight of each observed cold-start hold duration
_COLD_ALPHA = 0.3
#: Retry-After = observed cold start x this margin (the replica should
#: actually be up when the client re-dials, not merely almost)
_COLD_HINT_MARGIN = 1.25


class Activator:
    def __init__(self, platform, port: int = 0, host: str = "127.0.0.1",
                 activation_timeout_s: float = 45.0,
                 retry_after_s: float = 10.0, load_view=None):
        self.platform = platform
        self.host = host
        self.port = port
        #: explicit per-request deadline: a request held through a cold start
        #: that exceeds this gets 503 + Retry-After instead of holding the
        #: connection (and its server thread) forever
        self.activation_timeout_s = activation_timeout_s
        self.retry_after_s = retry_after_s
        #: optional queue-depth view: callable() -> {endpoint url: load}
        #: (the fleet router's load_view mapped to urls — docs/serving.md).
        #: With a view, ready-endpoint picks go least-loaded instead of
        #: round-robin; falls back to platform.fleet_load_view when unset.
        self.load_view = load_view
        self._httpd: ThreadingHTTPServer | None = None
        self._rr: dict[str, int] = {}
        self._rr_mu = make_lock("activator.Activator._rr_mu")
        #: demand stamps lost to delete/conflict races (benign; countable)
        self.demand_signal_losses = 0
        #: EWMA of OBSERVED cold-start hold durations (request arrival →
        #: ready endpoint): calibrates the 503 Retry-After hint so storm
        #: clients back off proportionally to how long a cold start
        #: actually takes HERE, instead of a static guess; 0.0 =
        #: uncalibrated (the static retry_after_s stays the fallback)
        self.cold_start_ewma_s = 0.0

    # ------------------------------------------------------------- routing

    def _least_loaded(self, urls: list[str], n: int) -> str | None:
        """Queue-depth-aware pick: the endpoint with the smallest load in
        the router's view; unknown endpoints count as load 0 (fresh
        replicas attract traffic). Ties break by the rr counter so equal
        loads still spread."""
        view = self.load_view or getattr(
            self.platform, "fleet_load_view", None)
        if view is None or not urls:
            return None
        try:
            loads = view()
        except Exception:  # noqa: BLE001 — a broken view must not 500 the
            return None    # request path; fall back to round-robin
        ranked = sorted(urls, key=lambda u: loads.get(u, 0))
        floor = loads.get(ranked[0], 0)
        tied = [u for u in ranked if loads.get(u, 0) == floor]
        return tied[n % len(tied)]

    def _pick_endpoint(self, isvc) -> str | None:
        """Canary-weighted pick over ready endpoints: canary endpoints
        receive canaryTrafficPercent of requests when both sets are
        ready; within a set the pick is least-loaded when a fleet load
        view is wired, round-robin otherwise."""
        primary = [e.url for e in isvc.status.endpoints if e.ready]
        canary = [e.url for e in isvc.status.canary_endpoints if e.ready]
        key = f"{isvc.metadata.namespace}/{isvc.metadata.name}"
        with self._rr_mu:
            n = self._rr[key] = self._rr.get(key, -1) + 1
        pct = isvc.spec.canary_traffic_percent
        pool = (canary if canary and pct > 0
                and (primary == [] or (n % 100) < pct) else primary)
        if not pool:
            return None
        return self._least_loaded(pool, n) or pool[n % len(pool)]

    def _signal_demand(self, key: str) -> None:
        def stamp(isvc):
            isvc.metadata.annotations[DEMAND_ANNOTATION] = \
                f"{time.time():.3f}"
            return isvc

        from kubeflow_tpu.controller.fakecluster import ConflictError

        try:
            self.platform.cluster.read_modify_write(
                "inferenceservices", key, stamp)
        except (KeyError, ConflictError):
            # deleted mid-request (handle() will 404/503) or hot
            # contention — the endpoint poll below still observes
            # scale-up; counted so a demand-stamp storm is visible
            self.demand_signal_losses += 1

    def _await_endpoint(self, key: str, deadline: Deadline) -> str | None:
        """Hold the request through a cold start: demand is signalled, then
        the ISVC status is polled under the shared jittered-backoff policy
        until a ready endpoint appears or the request deadline lapses."""
        cluster = self.platform.cluster
        self._signal_demand(key)
        _gone = object()  # service deleted mid-hold: stop early, not timeout

        def probe():
            isvc = cluster.get("inferenceservices", key)
            if isvc is None:
                return _gone
            return self._pick_endpoint(isvc)

        try:
            out = poll_until(
                probe,
                timeout_s=deadline.remaining(floor=0.0),
                policy=COLD_START_POLL,
                describe=f"ready endpoint for {key}",
            )
        except TimeoutError:
            return None
        return None if out is _gone else out

    def observe_cold_start(self, duration_s: float) -> None:
        """Feed one successful cold-start hold into the EWMA (handle()
        calls this when a held request actually got an endpoint — a
        timeout is censored, not a sample)."""
        if duration_s <= 0.0:
            return
        self.cold_start_ewma_s = (
            duration_s if self.cold_start_ewma_s <= 0.0
            else (1 - _COLD_ALPHA) * self.cold_start_ewma_s
            + _COLD_ALPHA * duration_s)

    def retry_after_hint_s(self) -> int:
        """The 503 Retry-After hint: observed-cold-start EWMA with a
        margin when calibrated (clients re-dial about when a replica
        will really be ready — a storm backs off proportionally), the
        static configured value as the uncalibrated fallback. Never
        above the static value: the config is the operator's ceiling."""
        ceiling = int(self.retry_after_s)
        if self.cold_start_ewma_s <= 0.0 or ceiling < 1:
            # uncalibrated — or a sub-second configured ceiling, where
            # the 1s floor below would EXCEED the operator's value
            return ceiling
        import math

        hinted = math.ceil(self.cold_start_ewma_s * _COLD_HINT_MARGIN)
        return max(1, min(hinted, ceiling))

    def _unavailable(self, msg: str) -> tuple[int, bytes, str, dict]:
        """503 with an explicit Retry-After: the client re-dials after the
        hint instead of the activator holding its connection forever."""
        return (
            503,
            f'{{"error": "{msg}"}}'.encode(),
            "application/json",
            {"Retry-After": str(self.retry_after_hint_s())},
        )

    def handle(self, method: str, path: str, body: bytes | None,
               content_type: str) -> tuple[int, bytes, str, dict]:
        """-> (status, payload, content-type, extra headers)."""
        deadline = Deadline(self.activation_timeout_s)
        parts = path.lstrip("/").split("/", 2)
        if len(parts) < 3:
            return 404, b'{"error": "route is /<namespace>/<name>/<path>"}', \
                "application/json", {}
        ns, name, rest = parts
        key = f"{ns}/{name}"
        isvc = self.platform.cluster.get("inferenceservices", key)
        if isvc is None:
            with self._rr_mu:  # deleted service: drop its rr counter so a
                self._rr.pop(key, None)  # long-lived activator never leaks
            return 404, f'{{"error": "inferenceservice {key} not found"}}' \
                .encode(), "application/json", {}
        url = self._pick_endpoint(isvc)
        if url is None:
            # cold start: the whole request-hold window is one span, so
            # activation latency renders alongside the controller's
            # scale-up work in the same timeline
            from kubeflow_tpu.tracing import tracer_of

            with tracer_of(self.platform).span(
                "activator.cold_start_hold", isvc=key,
            ) as sp:
                t0 = time.monotonic()
                url = self._await_endpoint(key, deadline)
                if url is not None:
                    # a COMPLETED hold calibrates the Retry-After hint
                    # (a timeout is censored — it proves nothing about
                    # how long a successful cold start takes)
                    self.observe_cold_start(time.monotonic() - t0)
                sp.set_attribute("outcome",
                                 "ready" if url is not None else "timeout")
        if url is None:
            return self._unavailable(
                "activation timed out: no replica became ready"
            )

        def proxy(endpoint: str):
            req = urllib.request.Request(
                f"{endpoint}/{rest}", data=body, method=method,
                headers={"Content-Type": content_type} if body else {},
            )
            try:
                with urllib.request.urlopen(req, timeout=60.0) as r:
                    return r.status, r.read(), \
                        r.headers.get("Content-Type", "application/json"), {}
            except urllib.error.HTTPError as e:
                return e.code, e.read(), \
                    e.headers.get("Content-Type", "application/json"), {}
            except (urllib.error.URLError, OSError):
                return None  # transport failure — caller decides

        # replica died between probe and proxy: bounded retries on the
        # shared BackoffPolicy, every sleep AND every re-pick clamped to
        # the SAME request deadline (self-heal will restore the replica;
        # the fleet load view keeps re-picks off the corpse's queue)
        for attempt in range(PROXY_RETRY.max_attempts + 1):
            out = proxy(url)
            if out is not None:
                return out
            if attempt >= PROXY_RETRY.max_attempts or deadline.expired():
                break
            backoff_sleep(PROXY_RETRY, attempt, deadline=deadline)
            url = self._await_endpoint(key, deadline)
            if url is None:
                return self._unavailable("no ready replica")
        return 502, b'{"error": "replica unreachable"}', \
            "application/json", {}

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "Activator":
        activator = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                print(f"[activator] {fmt % args}", flush=True)

            def _serve(self, method: str):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else None
                code, payload, ctype, extra = activator.handle(
                    method, self.path, body,
                    self.headers.get("Content-Type", "application/json"),
                )
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                for name, value in extra.items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._serve("GET")

            def do_POST(self):  # noqa: N802
                self._serve("POST")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
