"""Activator — the serverless front door (Knative activator analogue).

Reference parity (unverified cites, SURVEY.md §2.5/§3.5): kserve's
serverless mode rides Knative, whose activator buffers requests for a
revision scaled to zero, pokes the autoscaler, and proxies once a pod is
up. The TPU rebuild keeps the platform semantics: ONE stable URL per
InferenceService (`/<namespace>/<name>/<v1|v2 path>`) that

  - round-robins ready predictor endpoints, honoring the canary traffic
    split (the istio VirtualService weight analogue),
  - at zero ready replicas stamps a demand annotation on the ISVC (the
    controller's scale-from-zero trigger), HOLDS the request through the
    cold start, and proxies when an endpoint appears — AOT-exported
    predictors make that window compile-free (serving/aot.py).
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

#: annotation the activator stamps (epoch seconds) when a request arrives
#: for a scaled-to-zero service; the controller reads it as demand
DEMAND_ANNOTATION = "serving.kubeflow-tpu.org/activator-demand"


class Activator:
    def __init__(self, platform, port: int = 0, host: str = "127.0.0.1",
                 activation_timeout_s: float = 45.0):
        self.platform = platform
        self.host = host
        self.port = port
        self.activation_timeout_s = activation_timeout_s
        self._httpd: ThreadingHTTPServer | None = None
        self._rr: dict[str, int] = {}
        self._rr_mu = threading.Lock()

    # ------------------------------------------------------------- routing

    def _pick_endpoint(self, isvc) -> str | None:
        """Weighted round-robin: canary endpoints receive
        canaryTrafficPercent of requests when both sets are ready."""
        primary = [e.url for e in isvc.status.endpoints if e.ready]
        canary = [e.url for e in isvc.status.canary_endpoints if e.ready]
        key = f"{isvc.metadata.namespace}/{isvc.metadata.name}"
        with self._rr_mu:
            n = self._rr[key] = self._rr.get(key, -1) + 1
        pct = isvc.spec.canary_traffic_percent
        if canary and pct > 0 and (primary == [] or (n % 100) < pct):
            return canary[n % len(canary)]
        if primary:
            return primary[n % len(primary)]
        return None

    def _signal_demand(self, key: str) -> None:
        def stamp(isvc):
            isvc.metadata.annotations[DEMAND_ANNOTATION] = \
                f"{time.time():.3f}"
            return isvc

        from kubeflow_tpu.controller.fakecluster import ConflictError

        try:
            self.platform.cluster.read_modify_write(
                "inferenceservices", key, stamp)
        except (KeyError, ConflictError):
            pass  # deleted mid-request (handle() will 404/503) or hot
            # contention — the endpoint poll below still observes scale-up

    def _await_endpoint(self, key: str) -> str | None:
        """Hold the request through a cold start: demand is signalled,
        then the ISVC status is polled until a ready endpoint appears."""
        cluster = self.platform.cluster
        deadline = time.monotonic() + self.activation_timeout_s
        self._signal_demand(key)
        while time.monotonic() < deadline:
            isvc = cluster.get("inferenceservices", key)
            if isvc is None:
                return None
            url = self._pick_endpoint(isvc)
            if url is not None:
                return url
            time.sleep(0.15)
        return None

    def handle(self, method: str, path: str, body: bytes | None,
               content_type: str) -> tuple[int, bytes, str]:
        parts = path.lstrip("/").split("/", 2)
        if len(parts) < 3:
            return 404, b'{"error": "route is /<namespace>/<name>/<path>"}', \
                "application/json"
        ns, name, rest = parts
        key = f"{ns}/{name}"
        isvc = self.platform.cluster.get("inferenceservices", key)
        if isvc is None:
            with self._rr_mu:  # deleted service: drop its rr counter so a
                self._rr.pop(key, None)  # long-lived activator never leaks
            return 404, f'{{"error": "inferenceservice {key} not found"}}' \
                .encode(), "application/json"
        url = self._pick_endpoint(isvc)
        if url is None:
            url = self._await_endpoint(key)
        if url is None:
            return 503, b'{"error": "activation timed out: no replica became ready"}', \
                "application/json"

        def proxy(endpoint: str):
            req = urllib.request.Request(
                f"{endpoint}/{rest}", data=body, method=method,
                headers={"Content-Type": content_type} if body else {},
            )
            try:
                with urllib.request.urlopen(req, timeout=60.0) as r:
                    return r.status, r.read(), \
                        r.headers.get("Content-Type", "application/json")
            except urllib.error.HTTPError as e:
                return e.code, e.read(), \
                    e.headers.get("Content-Type", "application/json")
            except (urllib.error.URLError, OSError):
                return None  # transport failure — caller decides

        out = proxy(url)
        if out is not None:
            return out
        # replica died between probe and proxy: one retry through the
        # cold-start wait (self-heal will restore it)
        retry = self._await_endpoint(key)
        if retry is None:
            return 503, b'{"error": "no ready replica"}', "application/json"
        out = proxy(retry)
        if out is not None:
            return out
        return 502, b'{"error": "replica unreachable"}', "application/json"

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "Activator":
        activator = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                print(f"[activator] {fmt % args}", flush=True)

            def _serve(self, method: str):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else None
                code, payload, ctype = activator.handle(
                    method, self.path, body,
                    self.headers.get("Content-Type", "application/json"),
                )
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._serve("GET")

            def do_POST(self):  # noqa: N802
                self._serve("POST")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
