"""Model API + the in-tree JAX predictor runtime.

Reference parity (unverified cites, SURVEY.md §2.5): kserve
python/kserve/kserve/model.py Model{load, preprocess, predict, postprocess}
— the lifecycle a custom predictor implements — plus the framework-runtime
wrappers (python/sklearnserver etc.), whose TPU-relevant analogue is a
JAX/flax predictor that jit-compiles (XLA) at load and serves from the
device (SURVEY.md §2.5 'XLA-AOT-compiled model on a TPU nodepool').
"""

from __future__ import annotations

import importlib
import json
from pathlib import Path
from typing import Any

import numpy as np


class Model:
    """Base predictor. Subclass and override load/predict (and optionally
    preprocess/postprocess); the server drives the full chain per request."""

    def __init__(self, name: str):
        self.name = name
        self.ready = False

    def load(self) -> None:
        self.ready = True

    def preprocess(self, inputs: Any) -> Any:
        return inputs

    def predict(self, inputs: Any) -> Any:
        raise NotImplementedError

    def postprocess(self, outputs: Any) -> Any:
        return outputs

    def explain(self, inputs: Any) -> Any:
        """kserve :explain contract: override in an explainer model.
        `self.predict_fn` (bound by the server when an explainer wraps a
        predictor) calls the underlying predictor."""
        raise NotImplementedError(f"model {self.name!r} has no explainer")

    def __call__(self, inputs: Any) -> Any:
        return self.postprocess(self.predict(self.preprocess(inputs)))


class ExplainedModel(Model):
    """Explainer hop (kserve explainer analogue, in-process): predict flows
    through the predictor; :explain calls the explainer with a handle on the
    predictor chain (black-box explainers perturb inputs through it)."""

    def __init__(self, name: str, predictor: Model, explainer: Model):
        super().__init__(name)
        self.predictor = predictor
        self.explainer = explainer
        self.explainer.predict_fn = predictor  # callable chain handle

    def load(self) -> None:
        if not self.predictor.ready:
            self.predictor.load()
        if not self.explainer.ready:
            self.explainer.load()
        self.ready = True

    def predict(self, inputs: Any) -> Any:
        return self.predictor(inputs)

    def explain(self, inputs: Any) -> Any:
        return self.explainer.explain(inputs)


def load_model_class(path: str) -> type[Model]:
    """Import 'package.module:ClassName' (custom-runtime contract)."""
    mod_name, _, cls_name = path.partition(":")
    if not cls_name:
        raise ValueError(f"modelClass {path!r} must look like 'module:Class'")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    if not issubclass(cls, Model):
        raise TypeError(f"{path} is not a kubeflow_tpu.serving.Model subclass")
    return cls


class TransformedModel(Model):
    """Transformer hop (kserve transformer analogue, in-process): the
    transformer's preprocess/postprocess wrap the predictor's full chain."""

    def __init__(self, name: str, predictor: Model, transformer: Model):
        super().__init__(name)
        self.predictor = predictor
        self.transformer = transformer

    def load(self) -> None:
        if not self.predictor.ready:
            self.predictor.load()
        if not self.transformer.ready:
            self.transformer.load()
        self.ready = True

    def preprocess(self, inputs: Any) -> Any:
        return self.transformer.preprocess(inputs)

    def predict(self, inputs: Any) -> Any:
        return self.predictor(inputs)

    def postprocess(self, outputs: Any) -> Any:
        return self.transformer.postprocess(outputs)


# ------------------------------------------------------------ JAX runtime

CONFIG_FILE = "config.json"
PARAMS_FILE = "params.msgpack"


def _build_family(family: str, kwargs: dict):
    """In-tree model registry for the jax runtime (models/ package)."""
    from kubeflow_tpu import models as M

    if family == "mnist-mlp":
        return M.MnistMLP(**kwargs)
    if family == "mnist-cnn":
        return M.MnistCNN(**kwargs)
    if family.startswith("resnet"):
        ctor = {
            "resnet18": M.ResNet18, "resnet34": M.ResNet34,
            "resnet50": M.ResNet50, "resnet101": M.ResNet101,
            "resnet152": M.ResNet152,
        }[family]
        return ctor(**kwargs)
    if family == "bert-classifier":
        cfg_kw = kwargs.pop("config", {})
        cfg = M.BertConfig.tiny(**cfg_kw) if kwargs.pop("size", "tiny") == "tiny" \
            else M.BertConfig.base(**cfg_kw)
        return M.BertForSequenceClassification(cfg=cfg, **kwargs)
    if family == "gpt-lm":
        from kubeflow_tpu.models.gpt import GPTConfig, GPTLM

        cfg_kw = kwargs.pop("config", {})
        cfg = GPTConfig.tiny(**cfg_kw) if kwargs.pop("size", "tiny") == "tiny" \
            else GPTConfig.small(**cfg_kw)
        return GPTLM(cfg, **kwargs)
    if family == "vit-classifier":
        cfg_kw = kwargs.pop("config", {})
        cfg = M.ViTConfig.tiny(**cfg_kw) if kwargs.pop("size", "tiny") == "tiny" \
            else M.ViTConfig.base(**cfg_kw)
        return M.ViTClassifier(cfg, **kwargs)
    raise ValueError(f"unknown model family {family!r}")


def save_predictor(
    model_dir: str | Path,
    family: str,
    variables: dict,
    example_input: np.ndarray,
    generate: dict | None = None,
    quantize: bool = False,
    **family_kwargs,
) -> Path:
    """Write the jax-runtime model-dir contract: config.json (family +
    kwargs + example input signature) and params.msgpack (all variable
    collections). `variables` is {'params': ..., maybe 'batch_stats': ...}.

    generate: for causal-LM families, decode parameters (max_new_tokens,
    temperature, top_k, eos_token_id — rows clamp to EOS after emitting
    it, incompatible with num_beams > 1) — the predictor then serves
    token GENERATION (ids in -> generated ids out, KV-cache decode loop)
    instead of logits.

    quantize: int8 weight-only artifact (~4x smaller params.msgpack;
    per-output-channel scales, dequantized once at load — serving/quant.py)."""
    from flax import serialization

    d = Path(model_dir)
    d.mkdir(parents=True, exist_ok=True)
    example = np.asarray(example_input)
    cfg = {
        "family": family,
        "kwargs": family_kwargs,
        "input_shape": list(example.shape),
        "input_dtype": str(example.dtype),
    }
    if generate is not None:
        cfg["generate"] = generate
    if quantize:
        from kubeflow_tpu.serving.quant import quantize_variables

        cfg["quantized"] = True
        variables = quantize_variables(dict(variables))
    (d / CONFIG_FILE).write_text(json.dumps(cfg, indent=2))
    (d / PARAMS_FILE).write_bytes(serialization.to_bytes(variables))
    return d


def load_generative_model(model_dir: Path):
    """(module, variables, config) rebuilt from a model-dir — the raw
    pieces compositional decode paths consume (e.g. speculative decoding:
    `kubeflow_tpu generate --draft-model-dir`)."""
    import inspect

    import jax
    import jax.numpy as jnp
    from flax import serialization

    model_dir = Path(model_dir)
    config = json.loads((model_dir / CONFIG_FILE).read_text())
    module = _build_family(config["family"], dict(config["kwargs"]))
    example = np.zeros(config["input_shape"], dtype=config["input_dtype"])
    kwargs = {}
    if "train" in inspect.signature(module.__call__).parameters:
        kwargs["train"] = False
    target = module.init(jax.random.PRNGKey(0), jnp.asarray(example), **kwargs)
    raw = (model_dir / PARAMS_FILE).read_bytes()
    if config.get("quantized"):
        # int8 artifact: its tree shape differs from the module's, so
        # restore target-free, dequantize, then cast to the target's leaf
        # dtypes (serving/quant.py)
        from kubeflow_tpu.serving.quant import dequantize_variables

        deq = dequantize_variables(serialization.msgpack_restore(raw))
        variables = jax.tree.map(
            lambda t, x: jnp.asarray(x, t.dtype), target, deq
        )
    else:
        variables = serialization.from_bytes(target, raw)
    return module, variables, config


def _load_predict_fn(model_dir: Path):
    """Rebuild the flax predictor from the model-dir contract. Returns
    (predict_fn, config, example) — the one definition both the jit-at-load
    path and the AOT exporter (serving/aot.py) compile from."""
    import inspect

    module, variables, config = load_generative_model(model_dir)
    example = np.zeros(config["input_shape"], dtype=config["input_dtype"])
    kwargs = {}
    if "train" in inspect.signature(module.__call__).parameters:
        kwargs["train"] = False

    gen = config.get("generate")
    if gen is not None:
        from kubeflow_tpu.models.gpt import beam_search as _beam_search
        from kubeflow_tpu.models.gpt import generate as _generate

        temperature = float(gen.get("temperature", 0.0))
        num_beams = int(gen.get("num_beams", 1))
        if num_beams > 1 and temperature > 0.0:
            raise ValueError(
                "generate config: num_beams > 1 and temperature > 0 are "
                "mutually exclusive (beam search is deterministic)"
            )
        eos_raw = gen.get("eos_token_id")
        # int or a stop-id list (Llama-3 imports) — generate() takes both
        eos_id = (None if eos_raw is None
                  else [int(x) for x in eos_raw]
                  if isinstance(eos_raw, (list, tuple)) else int(eos_raw))
        if num_beams > 1 and eos_id is not None:
            raise ValueError(
                "generate config: eos_token_id is not supported with "
                "num_beams > 1 (beam search scores full-length beams)"
            )
        if num_beams > 1:
            def predict_fn(x):
                ids, _ = _beam_search(
                    module, variables, x,
                    max_new_tokens=int(gen.get("max_new_tokens", 32)),
                    num_beams=num_beams,
                )
                return ids
        elif temperature > 0.0:
            # per-REQUEST key (passed as a traced argument, derived by the
            # caller from seed + a call counter): a key baked into the jit
            # closure would replay the identical "sample" on every request
            def predict_fn(x, key):
                return _generate(
                    module, variables, x,
                    max_new_tokens=int(gen.get("max_new_tokens", 32)),
                    temperature=temperature,
                    top_k=int(gen.get("top_k", 0)),
                    rng=key,
                    eos_token_id=eos_id,
                )
        else:
            def predict_fn(x):
                return _generate(
                    module, variables, x,
                    max_new_tokens=int(gen.get("max_new_tokens", 32)),
                    eos_token_id=eos_id,
                )
    else:
        def predict_fn(x):
            return module.apply(variables, x, **kwargs)

    return predict_fn, config, example


class JaxModel(Model):
    """In-tree-family predictor.

    Load prefers a deploy-time AOT artifact (serving/aot.py: serialized
    jax.export with params baked in — no module rebuild, no params restore,
    no Python retrace; with a warmed persistent compile cache the process
    performs zero backend compilations). Without an artifact it falls back
    to rebuilding the module and jit-compiling at load (warmup on the
    recorded example shape, so the first request pays no compile)."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._predict_fn = None
        self._aot_batch: int | None = None
        self._engine = None  # continuous-batching decode engine
        self._fleet = None   # multi-replica fleet router (serving/fleet)
        self.config: dict = {}

    def load(self) -> None:
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.serving import aot

        cfg_path = self.model_dir / CONFIG_FILE
        cfg = json.loads(cfg_path.read_text()) if cfg_path.exists() else {}
        gen = cfg.get("generate") or {}
        if gen.get("continuous"):
            # continuous batching (serving/continuous.py): concurrent
            # requests interleave decode steps on one fixed-row engine
            # instead of serializing whole decodes. Greedy or sampling
            # (per-request keys, engine-static top_k); jit path (the
            # engine's executables splice rows — not exportable as one
            # fixed computation).
            if int(gen.get("num_beams", 1)) > 1:
                raise ValueError(
                    "generate config: continuous batching does not "
                    "compose with beam search (num_beams == 1)")
            from kubeflow_tpu.serving.continuous import ContinuousBatcher

            module, variables, self.config = load_generative_model(
                self.model_dir)
            eos = gen.get("eos_token_id")
            # speculative continuous serving: a second model dir provides
            # the draft (same pattern as the CLI's --draft-model-dir);
            # relative paths resolve against the target's model dir
            draft_module = draft_variables = None
            if gen.get("continuous_draft_dir"):
                ddir = Path(gen["continuous_draft_dir"])
                if not ddir.is_absolute():
                    ddir = self.model_dir / ddir
                draft_module, draft_variables, _ = load_generative_model(
                    ddir)
            # fleet extensions (docs/serving.md): chunked prefill, a
            # per-model paged-KV pool for prefix reuse, and with
            # fleet_replicas > 1 a FleetRouter over N engines sharing the
            # pool — SLO admission sheds surface as 503 + Retry-After
            paged_kv = None
            if int(gen.get("paged_kv_block", 0)) > 0:
                from kubeflow_tpu.serving.fleet import PagedKVPool

                paged_kv = PagedKVPool(
                    block_size=int(gen["paged_kv_block"]),
                    capacity_blocks=int(
                        gen.get("paged_kv_capacity_blocks", 1024)))

            def build_engine():
                return ContinuousBatcher(
                    module, variables,
                    max_rows=int(gen.get("continuous_rows", 8)),
                    default_max_new_tokens=int(
                        gen.get("max_new_tokens", 32)),
                    # int or stop-id list — the engine normalizes either
                    eos_token_id=eos,
                    top_k=int(gen.get("top_k", 0)),
                    seed=int(gen.get("seed", 0)),
                    steps_per_tick=int(
                        gen.get("continuous_steps_per_tick", 1)),
                    prefill_buckets=(
                        tuple(gen["continuous_prefill_buckets"])
                        if gen.get("continuous_prefill_buckets") else None),
                    draft_module=draft_module,
                    draft_variables=draft_variables,
                    gamma=int(gen.get("speculative_gamma", 4)),
                    prefill_chunk=int(gen.get("prefill_chunk", 0)),
                    paged_kv=paged_kv,
                )

            n_replicas = int(gen.get("fleet_replicas", 1))
            if n_replicas > 1:
                from kubeflow_tpu.serving.fleet import FleetRouter

                self._fleet = FleetRouter(
                    [build_engine() for _ in range(n_replicas)],
                    ttft_slo_s=float(gen.get("fleet_ttft_slo_s", 0.0)),
                    retry_after_s=float(
                        gen.get("fleet_retry_after_s", 1.0)),
                ).start()
            else:
                self._engine = build_engine().start()
            self.ready = True
            return

        if aot.aot_available(self.model_dir):
            self.config = json.loads((self.model_dir / CONFIG_FILE).read_text())
            meta = json.loads((self.model_dir / aot.AOT_META).read_text())
            call = aot.load_exported(self.model_dir)
            self._aot_batch = int(meta["batch_size"])
            example = np.zeros(
                self.config["input_shape"], dtype=self.config["input_dtype"]
            )
            # warmup executes the serialized computation once (backend
            # compile — a cache hit when the deploy step warmed the cache)
            np.asarray(call(jnp.asarray(example)))
            self._predict_fn = call
            self.ready = True
            return

        predict_fn, self.config, example = _load_predict_fn(self.model_dir)
        predict_fn = jax.jit(predict_fn)
        # warmup: trace+compile on the recorded signature
        if self._sampling:
            jax.block_until_ready(
                predict_fn(jnp.asarray(example), jax.random.PRNGKey(0)))
        else:
            predict_fn(jnp.asarray(example)).block_until_ready()
        self._predict_fn = predict_fn
        self.ready = True

    @property
    def _sampling(self) -> bool:
        gen = self.config.get("generate")
        return gen is not None and float(gen.get("temperature", 0.0)) > 0.0

    def predict(self, inputs: np.ndarray) -> np.ndarray:
        x = np.asarray(inputs, dtype=self.config["input_dtype"])
        gen = self.config.get("generate")
        if gen is not None:
            pad = int(gen.get("pad_token_id", 0))
            if (x == pad).any():
                # the decode path has no pad masking (positions are cache-
                # indexed); a padded prompt would write pads into the KV
                # cache and sample from a pad position — reject loudly
                raise ValueError(
                    f"generation prompts must not contain the pad token id "
                    f"{pad}: send equal-length unpadded prompts"
                )
        if getattr(self, "_engine", None) is not None \
                or getattr(self, "_fleet", None) is not None:
            out, _ = self._engine_predict_timed(x, gen)
            return out
        if self._sampling:
            import jax

            seed = int(gen.get("seed", 0))
            # per-request key: seed folds with a monotonically advancing
            # call counter so repeated requests sample fresh completions
            self._calls = getattr(self, "_calls", 0) + 1
            key = jax.random.fold_in(jax.random.PRNGKey(seed), self._calls)
            return np.asarray(self._predict_fn(x, key))
        if self._aot_batch is not None:
            from kubeflow_tpu.serving import aot

            want = tuple(self.config["input_shape"][1:])
            if gen is not None and tuple(x.shape[1:]) != want:
                # generation prompts cannot pad (decode masks by position,
                # not pad id), so the exported fixed shape is a hard
                # contract along every non-batch dim
                raise ValueError(
                    f"AOT generative artifact is fixed to prompt shape "
                    f"{want}; got {tuple(x.shape[1:])} — send "
                    f"{want[0]}-token prompts or serve via the jit path "
                    f"(delete {aot.AOT_FILE})"
                )
            return aot.padded_chunk_predict(self._predict_fn, x, self._aot_batch)
        return np.asarray(self._predict_fn(x))

    def _engine_predict_timed(self, x: np.ndarray, gen: dict):
        """Engine/fleet decode for a prompt batch, with the streaming
        timing the load-test client reads: ({rows}, {"ttft_s",
        "tokens_per_s"}). Fleet admission sheds (FleetOverloaded)
        propagate — the server maps them to 503 + Retry-After."""
        budget = int(gen.get("max_new_tokens", 32))
        eos = gen.get("eos_token_id")
        temp = float(gen.get("temperature", 0.0))
        if self._fleet is not None:
            # gate ONCE with the whole batch's prompt work, then submit
            # ungated: a shed on row k would otherwise orphan the k rows
            # already admitted — decode capacity burned on answers
            # nobody reads, exactly what admission control exists to
            # prevent. A shed here is traced like a submit()-path shed
            # (record_shed), so the 503 body carries the decision's
            # span ctx + request id.
            from kubeflow_tpu.serving.fleet import FleetOverloaded

            batch_tokens = int(sum(len(row) for row in x))
            try:
                self._fleet.admit_or_raise(batch_tokens)
            except FleetOverloaded as exc:
                raise self._fleet.record_shed(exc, batch_tokens)
            submit = lambda row, **kw: self._fleet.submit(  # noqa: E731
                row, gate=False, **kw)
        else:
            submit = self._engine.submit
        reqs = [submit(row, max_new_tokens=budget, temperature=temp)
                for row in x]
        # eos may be a stop-id LIST (Llama-3 imports); the clamp
        # token past a retired row is the FIRST id — generate()'s
        # contract
        clamp = (int(eos[0]) if isinstance(eos, (list, tuple))
                 else None if eos is None else int(eos))
        outs = []
        for r in reqs:
            ids = r.result(timeout=300.0)
            if ids.size < budget:  # pad past the stop with the clamp
                ids = np.concatenate([
                    ids, np.full((budget - ids.size,), clamp,
                                 np.int32)])
            outs.append(ids)
        ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
        rates = [r.tokens_per_s for r in reqs
                 if r.tokens_per_s not in (None, float("inf"))]
        timing = {
            "ttft_s": round(min(ttfts), 6) if ttfts else None,
            "tokens_per_s": (round(sum(rates), 3) if rates else None),
        }
        return np.stack(outs), timing

    def close(self) -> None:
        """Stop the engine/fleet ticker threads (server shutdown path)."""
        if self._engine is not None:
            self._engine.stop()
        if self._fleet is not None:
            self._fleet.stop()

    def predict_timed(self, inputs: np.ndarray):
        """predict() plus per-request streaming timing when an engine or
        fleet serves the model — (output, timing|None). The v1 server
        surfaces the timing so clients (ServingClient.predict_timed)
        measure TTFT from the engine's own token timestamps instead of
        guessing from HTTP wall time."""
        x = np.asarray(inputs, dtype=self.config["input_dtype"])
        gen = self.config.get("generate")
        if gen is not None and (self._engine is not None
                                or self._fleet is not None):
            pad = int(gen.get("pad_token_id", 0))
            if (x == pad).any():
                raise ValueError(
                    f"generation prompts must not contain the pad token id "
                    f"{pad}: send equal-length unpadded prompts"
                )
            return self._engine_predict_timed(x, gen)
        return self.predict(inputs), None

    def postprocess(self, outputs: np.ndarray) -> dict:
        """Classification contract: logits -> class + per-class scores.
        Generative configs return the generated token ids directly."""
        if self.config.get("generate") is not None:
            ids = np.asarray(outputs, dtype=np.int64)
            return {"predictions": ids.tolist()}
        logits = np.asarray(outputs, dtype=np.float32)
        return {
            "predictions": np.argmax(logits, axis=-1).tolist(),
            "logits": logits.tolist(),
        }
