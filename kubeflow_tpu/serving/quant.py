"""Int8 weight-only quantization for serving artifacts.

The reference serves float checkpoints; for TPU serving the dominant costs
are artifact bytes (storage initializer pull, HBM upload) and cold-start
time. Weight-only int8 cuts params.msgpack ~4x with symmetric per-output-
channel scales (the standard LLM serving recipe); weights dequantize ONCE
at load to the model dtype, so runtime numerics and speed are the float
path's — this is a transport/storage format, not a compute mode.

    save_predictor(..., quantize=True)       # writes int8 + scales
    JaxModel.load()                          # dequantizes transparently
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np
from flax import traverse_util

# quantize big matmul weights; leave LayerNorm/bias/small leaves float
DEFAULT_TARGETS = r"(kernel|embedding)$"
MIN_SIZE = 4096

_QKEY = "__int8__"  # marker key inside a quantized leaf's subtree


def quantize_variables(variables: dict, targets: str = DEFAULT_TARGETS) -> dict:
    """params tree -> same tree with matching leaves replaced by
    {_QKEY: 1, q: int8, scale: f32 per-output-channel}."""
    flat = traverse_util.flatten_dict(variables, sep="/")
    out: dict[str, Any] = {}
    for path, w in flat.items():
        arr = np.asarray(w)
        if (re.search(targets, path) and arr.ndim >= 2
                and arr.size >= MIN_SIZE
                and arr.dtype.kind == "f"):
            a32 = arr.astype(np.float32)
            if re.search(r"embedding$", path):
                # per-ROW (per-token) scales: a shared per-feature scale
                # would let the largest-magnitude token set the resolution
                # for every rare small-norm row (and the weight-tied LM
                # head reads this table for logits)
                absmax = np.max(np.abs(a32), axis=-1, keepdims=True)
            else:
                # symmetric per-output-channel (last dim) scales
                absmax = np.max(np.abs(a32), axis=tuple(range(arr.ndim - 1)))
            scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
            q = np.clip(np.rint(a32 / scale), -127, 127).astype(np.int8)
            out[path + "/" + _QKEY] = np.int8(1)
            out[path + "/q"] = q
            out[path + "/scale"] = scale
        else:
            out[path] = arr
    return traverse_util.unflatten_dict(out, sep="/")


def dequantize_variables(variables: dict, dtype=None) -> dict:
    """Inverse of quantize_variables: int8 leaves -> float weights (model
    dtype resolution happens at apply; dtype here optionally casts)."""
    flat = traverse_util.flatten_dict(variables, sep="/")
    out: dict[str, Any] = {}
    done = set()
    for path in list(flat):
        if not path.endswith("/" + _QKEY):
            continue
        base = path[: -(len(_QKEY) + 1)]
        q = np.asarray(flat[base + "/q"], np.float32)
        scale = np.asarray(flat[base + "/scale"], np.float32)
        w = q * scale  # broadcast over the last dim
        out[base] = w.astype(dtype) if dtype is not None else w
        done.update({path, base + "/q", base + "/scale"})
    for path, v in flat.items():
        if path not in done:
            out[path] = v
    return traverse_util.unflatten_dict(out, sep="/")


def is_quantized(variables: dict) -> bool:
    return any(
        p.endswith("/" + _QKEY)
        for p in traverse_util.flatten_dict(variables, sep="/")
    )


def quantization_error(variables: dict, quantized: dict) -> float:
    """Max relative per-tensor L2 error across quantized leaves (sanity
    metric: per-channel int8 on trained nets sits well under 1%)."""
    deq = dequantize_variables(quantized)
    a = traverse_util.flatten_dict(variables, sep="/")
    b = traverse_util.flatten_dict(deq, sep="/")
    worst = 0.0
    for path, w in a.items():
        w = np.asarray(w, np.float32)
        d = np.asarray(b[path], np.float32)
        denom = float(np.linalg.norm(w)) or 1.0
        worst = max(worst, float(np.linalg.norm(w - d)) / denom)
    return worst
