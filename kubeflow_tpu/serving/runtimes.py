"""Framework predictor runtimes — the kserve wrapper-zoo analogue.

Reference parity (unverified cites, SURVEY.md §2.5 "Framework runtimes"):
kserve ships python/{sklearnserver,xgbserver,lgbserver,paddleserver,...},
each a thin Model subclass that loads a serialized artifact from the
storage-initializer dir and serves predict. Here:

  - SklearnModel: joblib/pickle estimator (model.joblib | model.pkl),
    predict + predict_proba.
  - TorchModel: TorchScript (model.pt via torch.jit) or a pickled module
    (model.pth) on CPU — CUDA-free by design (north star: zero GPU pods);
    TPU-bound users convert to the jax runtime.
  - XGBoost/LightGBM: their upstream wrappers are one-liners over the same
    pattern; the packages are absent from this environment, so the runtimes
    raise a clear error at load (gated, not silently broken).

Select via `--runtime sklearn|torch` on the model server or
`predictor.runtime` in an InferenceService spec.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from kubeflow_tpu.serving.model import Model


class SklearnModel(Model):
    """sklearnserver parity: loads model.joblib / model.pkl, serves
    predict(); classifier outputs include probabilities when available."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._est = None

    def load(self) -> None:
        import joblib

        for fname in ("model.joblib", "model.pkl"):
            path = self.model_dir / fname
            if path.exists():
                self._est = joblib.load(path)
                break
        else:
            raise FileNotFoundError(
                f"no model.joblib/model.pkl under {self.model_dir}"
            )
        self.ready = True

    def predict(self, inputs):
        x = np.asarray(inputs)
        out = {"predictions": np.asarray(self._est.predict(x)).tolist()}
        if hasattr(self._est, "predict_proba"):
            out["probabilities"] = np.asarray(
                self._est.predict_proba(x)
            ).tolist()
        return out


class TorchModel(Model):
    """torchserve-shaped runtime on CPU: TorchScript model.pt preferred,
    pickled nn.Module model.pth accepted."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._mod = None

    def load(self) -> None:
        import torch

        pt, pth = self.model_dir / "model.pt", self.model_dir / "model.pth"
        if pt.exists():
            self._mod = torch.jit.load(str(pt), map_location="cpu")
        elif pth.exists():
            # weights_only=False: the artifact is a whole pickled module, the
            # torchserve-style contract (trusted model store, not user input)
            self._mod = torch.load(
                str(pth), map_location="cpu", weights_only=False
            )
        else:
            raise FileNotFoundError(f"no model.pt/model.pth under {self.model_dir}")
        self._mod.eval()
        self.ready = True

    def predict(self, inputs):
        import torch

        with torch.no_grad():
            out = self._mod(torch.as_tensor(np.asarray(inputs)))
        return out.numpy()


class XGBoostModel(Model):
    """xgbserver parity: Booster from model.bst / model.json / model.ubj."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._booster = None

    def load(self) -> None:
        try:
            import xgboost as xgb
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'xgboost' requires the xgboost package (absent in "
                "this image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        for fname in ("model.bst", "model.json", "model.ubj"):
            path = self.model_dir / fname
            if path.exists():
                self._booster = xgb.Booster()
                self._booster.load_model(str(path))
                break
        else:
            raise FileNotFoundError(
                f"no model.bst/model.json/model.ubj under {self.model_dir}"
            )
        self.ready = True

    def predict(self, inputs):
        import xgboost as xgb

        return self._booster.predict(
            xgb.DMatrix(np.asarray(inputs))
        ).tolist()


class LightGBMModel(Model):
    """lgbserver parity: Booster from model.txt."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._booster = None

    def load(self) -> None:
        try:
            import lightgbm as lgb
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'lightgbm' requires the lightgbm package (absent in "
                "this image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        path = self.model_dir / "model.txt"
        if not path.exists():
            raise FileNotFoundError(f"no model.txt under {self.model_dir}")
        self._booster = lgb.Booster(model_file=str(path))
        self.ready = True

    def predict(self, inputs):
        return self._booster.predict(np.asarray(inputs)).tolist()


class PaddleModel(Model):
    """paddleserver parity: inference model from model.pdmodel +
    model.pdiparams (gated: paddlepaddle is absent in this image)."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._predictor = None

    def load(self) -> None:
        try:
            import paddle.inference as paddle_infer
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'paddle' requires the paddlepaddle package (absent "
                "in this image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        pdmodel = self.model_dir / "model.pdmodel"
        pdparams = self.model_dir / "model.pdiparams"
        if not pdmodel.exists() or not pdparams.exists():
            raise FileNotFoundError(
                f"no model.pdmodel + model.pdiparams under {self.model_dir}"
            )
        config = paddle_infer.Config(str(pdmodel), str(pdparams))
        self._predictor = paddle_infer.create_predictor(config)
        self.ready = True

    def predict(self, inputs):
        x = np.asarray(inputs, dtype=np.float32)
        names = self._predictor.get_input_names()
        handle = self._predictor.get_input_handle(names[0])
        handle.reshape(x.shape)
        handle.copy_from_cpu(x)
        self._predictor.run()
        out = self._predictor.get_output_handle(
            self._predictor.get_output_names()[0]
        )
        return out.copy_to_cpu().tolist()


class PMMLModel(Model):
    """pmmlserver parity: PMML pipeline via pypmml (gated: absent here)."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._model = None

    def load(self) -> None:
        try:
            from pypmml import Model as PmmlModel
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'pmml' requires the pypmml package (absent in this "
                "image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        candidates = sorted(self.model_dir.glob("*.pmml"))
        if not candidates:
            raise FileNotFoundError(f"no *.pmml under {self.model_dir}")
        self._model = PmmlModel.load(str(candidates[0]))
        self.ready = True

    def predict(self, inputs):
        x = np.asarray(inputs)
        return [self._model.predict(list(map(float, row))) for row in x]


# --------------------------------------------------------- triton-shaped


def parse_config_pbtxt(text: str) -> dict:
    """Parse the subset of protobuf text format that triton's config.pbtxt
    uses: scalar fields (`name: "x"`, `max_batch_size: 8`), enum tokens
    (`data_type: TYPE_FP32`), repeated message blocks (`input [ {...} ]` or
    repeated `input { ... }`), and numeric lists (`dims: [ 3, 224 ]`).
    No protobuf dependency — the grammar is five constructs."""
    import re

    # strip '#' comments (legal and ubiquitous in triton configs) — but not
    # inside quoted strings
    stripped_lines = []
    for line in text.splitlines():
        out_chars: list[str] = []
        in_str = False
        i = 0
        while i < len(line):
            c = line[i]
            if c == '"' and (i == 0 or line[i - 1] != "\\"):
                in_str = not in_str
            elif c == "#" and not in_str:
                break
            out_chars.append(c)
            i += 1
        stripped_lines.append("".join(out_chars))
    text = "\n".join(stripped_lines)

    pos = 0
    # every character must land in a token — unmatched input raises instead
    # of silently desynchronizing the parser (text format has no recovery)
    _NUM = r"-?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?"
    token_re = re.compile(
        r'\s*(?:("(?:[^"\\]|\\.)*")|([\[\]{}:,])|([A-Za-z_][\w.]*)'
        rf"|({_NUM}))"
    )
    tokens: list[str] = []
    scan = 0
    while scan < len(text):
        m = token_re.match(text, scan)
        if m is None or m.end() == m.start():
            rest = text[scan:].lstrip()
            if not rest:
                break
            raise ValueError(
                f"config.pbtxt parse error near {rest[:20]!r} "
                f"(offset {scan})"
            )
        tok = next(g for g in m.groups() if g is not None)
        tokens.append(tok)
        scan = m.end()

    _num_int = re.compile(r"-?\d+")

    def parse_value():
        nonlocal pos
        if pos >= len(tokens):
            raise ValueError("config.pbtxt truncated: value expected")
        tok = tokens[pos]
        if tok == "{":
            return parse_block()
        if tok == "[":
            pos += 1
            items = []
            while pos < len(tokens) and tokens[pos] != "]":
                if tokens[pos] == ",":
                    pos += 1
                    continue
                items.append(parse_value())
            if pos >= len(tokens):
                raise ValueError("config.pbtxt truncated: unclosed '['")
            pos += 1
            return items
        pos += 1
        if tok.startswith('"'):
            return tok[1:-1]
        if _num_int.fullmatch(tok):
            return int(tok)
        if re.fullmatch(_NUM, tok):
            return float(tok)
        if tok in ("true", "false"):
            return tok == "true"
        return tok  # enum token, e.g. TYPE_FP32

    def parse_block():
        nonlocal pos
        assert tokens[pos] == "{"
        pos += 1
        # text-format repeated-field semantics: every occurrence contributes
        # items ('[...]' contributes its elements, anything else one item);
        # repeats CONCATENATE — `dims: [2] dims: [3]` == `dims: [2, 3]`
        items: dict[str, list] = {}
        listy: set[str] = set()
        while pos < len(tokens) and tokens[pos] != "}":
            key = tokens[pos]
            pos += 1
            if pos < len(tokens) and tokens[pos] == ":":
                pos += 1
            was_bracket = pos < len(tokens) and tokens[pos] == "["
            val = parse_value()
            new = val if was_bracket else [val]
            if key in items:
                items[key].extend(new)
                listy.add(key)
            else:
                items[key] = new
                if was_bracket:
                    listy.add(key)
        if pos >= len(tokens):
            raise ValueError("config.pbtxt truncated: unclosed '{'")
        pos += 1
        return {k: v if k in listy else v[0] for k, v in items.items()}

    _REPEATED = {"input", "output", "instance_group"}
    # wrap the file body in braces and reuse the block parser
    tokens = ["{"] + tokens + ["}"]
    cfg = parse_block()
    # normalize repeated-message fields to lists
    for key in _REPEATED:
        if key in cfg and isinstance(cfg[key], dict):
            cfg[key] = [cfg[key]]
    return cfg


TRITON_DTYPES = {
    "TYPE_FP32": np.float32, "TYPE_FP64": np.float64,
    "TYPE_FP16": np.float16, "TYPE_INT64": np.int64,
    "TYPE_INT32": np.int32, "TYPE_INT16": np.int16, "TYPE_INT8": np.int8,
    "TYPE_UINT8": np.uint8, "TYPE_BOOL": np.bool_,
}


class TritonModel(Model):
    """Triton-repository-shaped runtime (kserve's triton ServingRuntime
    analogue): serves a model laid out as

        <model_dir>/config.pbtxt
        <model_dir>/<version>/model.<ext>

    with config.pbtxt declaring platform, max_batch_size, and typed
    input/output tensors (the Open Inference Protocol contract — triton is
    the OIP reference server, so this runtime rides our v2 endpoints
    directly). The newest numeric version directory is loaded, as triton's
    default version policy does. Backends:

      - pytorch_libtorch: TorchScript model.pt (torch is in-image)
      - onnxruntime_onnx / tensorrt_plan: gated (packages absent here)

    Inputs: a dict name->array (multi-input) or a bare array (bound to the
    single declared input); dtypes/shapes validated against config.pbtxt.
    """

    GATED_PLATFORMS = {
        "onnxruntime_onnx": "onnxruntime",
        "tensorrt_plan": "tensorrt (GPU-only — out of scope on TPU)",
    }

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self.config: dict = {}
        self._mod = None

    # ------------------------------------------------------------- layout

    def _pick_version(self) -> Path:
        versions = sorted(
            (p for p in self.model_dir.iterdir()
             if p.is_dir() and p.name.isdigit()),
            key=lambda p: int(p.name),
        )
        if not versions:
            raise FileNotFoundError(
                f"no numeric version directory under {self.model_dir} "
                "(triton repository layout: <model>/<version>/model.<ext>)"
            )
        return versions[-1]

    def load(self) -> None:
        cfg_path = self.model_dir / "config.pbtxt"
        if not cfg_path.exists():
            raise FileNotFoundError(f"no config.pbtxt under {self.model_dir}")
        self.config = parse_config_pbtxt(cfg_path.read_text())
        platform = self.config.get("platform", "")
        vdir = self._pick_version()
        if platform == "pytorch_libtorch":
            import torch

            pt = vdir / "model.pt"
            if not pt.exists():
                raise FileNotFoundError(f"no model.pt under {vdir}")
            self._mod = torch.jit.load(str(pt), map_location="cpu")
            self._mod.eval()
        elif platform in self.GATED_PLATFORMS:
            raise ModuleNotFoundError(
                f"triton platform {platform!r} requires "
                f"{self.GATED_PLATFORMS[platform]}, absent in this image; "
                "convert the model to pytorch_libtorch or the jax runtime"
            )
        else:
            raise ValueError(
                f"unsupported triton platform {platform!r} "
                "(pytorch_libtorch|onnxruntime_onnx|tensorrt_plan)"
            )
        self.version = vdir.name
        self.ready = True

    # ------------------------------------------------------------ serving

    def _input_specs(self) -> list[dict]:
        return list(self.config.get("input", []))

    def _validate(self, name: str, arr: np.ndarray, spec: dict) -> np.ndarray:
        want = TRITON_DTYPES.get(spec.get("data_type", ""), None)
        if want is not None and arr.dtype != np.dtype(want):
            # safe widening/narrowing within a kind (f64->f32, i64->i32) and
            # int->float are accepted; value-destroying casts (float->int,
            # numeric->bool) are config mismatches, as triton rejects them
            ok = np.can_cast(arr.dtype, want, casting="same_kind") or (
                arr.dtype.kind in "iu" and np.dtype(want).kind == "f"
            )
            if not ok:
                raise ValueError(
                    f"input {name!r} dtype {arr.dtype} incompatible with "
                    f"declared {spec.get('data_type')}"
                )
            arr = arr.astype(want)
        dims = [int(d) for d in spec.get("dims", [])]
        # config dims exclude the batch dim when max_batch_size > 0
        mbs = int(self.config.get("max_batch_size", 0))
        batched = mbs > 0
        got = list(arr.shape[1:]) if batched else list(arr.shape)
        if dims and len(got) == len(dims):
            for g, w in zip(got, dims):
                if w != -1 and g != w:
                    raise ValueError(
                        f"input {name!r} shape {got} does not match "
                        f"config.pbtxt dims {dims}"
                    )
        elif dims:
            raise ValueError(
                f"input {name!r} rank {len(got)} does not match "
                f"config.pbtxt dims {dims}"
            )
        if batched and arr.shape[0] > mbs:
            raise ValueError(
                f"batch {arr.shape[0]} exceeds max_batch_size {mbs}"
            )
        return arr

    def predict(self, inputs):
        import torch

        specs = self._input_specs()
        if isinstance(inputs, dict):
            ordered = []
            for spec in specs:
                name = spec.get("name", "")
                if name not in inputs:
                    raise ValueError(f"missing input tensor {name!r}")
                ordered.append(self._validate(
                    name, np.asarray(inputs[name]), spec))
        else:
            arr = np.asarray(inputs)
            if len(specs) > 1:
                raise ValueError(
                    f"model declares {len(specs)} inputs; pass a dict of "
                    f"name->tensor ({[s.get('name') for s in specs]})"
                )
            ordered = [self._validate(
                specs[0].get("name", "input"), arr, specs[0])] if specs else [arr]
        with torch.no_grad():
            out = self._mod(*(torch.as_tensor(a) for a in ordered))
        outs = out if isinstance(out, (tuple, list)) else (out,)
        out_specs = self.config.get("output", [])
        names = [s.get("name", f"output_{i}") for i, s in enumerate(out_specs)]
        # a model returning more tensors than config declares must not have
        # the extras silently zip-truncated — name them positionally
        names += [f"output_{i}" for i in range(len(names), len(outs))]
        if len(outs) == 1 and not isinstance(inputs, dict):
            return outs[0].numpy()
        # named arrays: ModelServer.postprocess_arrays carries these through
        # the v2 surfaces as one output tensor per name
        return {n: o.numpy() for n, o in zip(names, outs)}


RUNTIMES: dict[str, type] = {
    "sklearn": SklearnModel,
    "torch": TorchModel,
    "xgboost": XGBoostModel,
    "lightgbm": LightGBMModel,
    "paddle": PaddleModel,
    "pmml": PMMLModel,
    "triton": TritonModel,
}


def build_runtime(runtime: str, name: str, model_dir: str | Path) -> Model:
    cls = RUNTIMES.get(runtime)
    if cls is None:
        raise ValueError(
            f"unknown runtime {runtime!r} (jax|custom|{'|'.join(RUNTIMES)})"
        )
    return cls(name, model_dir)
