"""Framework predictor runtimes — the kserve wrapper-zoo analogue.

Reference parity (unverified cites, SURVEY.md §2.5 "Framework runtimes"):
kserve ships python/{sklearnserver,xgbserver,lgbserver,paddleserver,...},
each a thin Model subclass that loads a serialized artifact from the
storage-initializer dir and serves predict. Here:

  - SklearnModel: joblib/pickle estimator (model.joblib | model.pkl),
    predict + predict_proba.
  - TorchModel: TorchScript (model.pt via torch.jit) or a pickled module
    (model.pth) on CPU — CUDA-free by design (north star: zero GPU pods);
    TPU-bound users convert to the jax runtime.
  - XGBoost/LightGBM: their upstream wrappers are one-liners over the same
    pattern; the packages are absent from this environment, so the runtimes
    raise a clear error at load (gated, not silently broken).

Select via `--runtime sklearn|torch` on the model server or
`predictor.runtime` in an InferenceService spec.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from kubeflow_tpu.serving.model import Model


class SklearnModel(Model):
    """sklearnserver parity: loads model.joblib / model.pkl, serves
    predict(); classifier outputs include probabilities when available."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._est = None

    def load(self) -> None:
        import joblib

        for fname in ("model.joblib", "model.pkl"):
            path = self.model_dir / fname
            if path.exists():
                self._est = joblib.load(path)
                break
        else:
            raise FileNotFoundError(
                f"no model.joblib/model.pkl under {self.model_dir}"
            )
        self.ready = True

    def predict(self, inputs):
        x = np.asarray(inputs)
        out = {"predictions": np.asarray(self._est.predict(x)).tolist()}
        if hasattr(self._est, "predict_proba"):
            out["probabilities"] = np.asarray(
                self._est.predict_proba(x)
            ).tolist()
        return out


class TorchModel(Model):
    """torchserve-shaped runtime on CPU: TorchScript model.pt preferred,
    pickled nn.Module model.pth accepted."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._mod = None

    def load(self) -> None:
        import torch

        pt, pth = self.model_dir / "model.pt", self.model_dir / "model.pth"
        if pt.exists():
            self._mod = torch.jit.load(str(pt), map_location="cpu")
        elif pth.exists():
            # weights_only=False: the artifact is a whole pickled module, the
            # torchserve-style contract (trusted model store, not user input)
            self._mod = torch.load(
                str(pth), map_location="cpu", weights_only=False
            )
        else:
            raise FileNotFoundError(f"no model.pt/model.pth under {self.model_dir}")
        self._mod.eval()
        self.ready = True

    def predict(self, inputs):
        import torch

        with torch.no_grad():
            out = self._mod(torch.as_tensor(np.asarray(inputs)))
        return out.numpy()


class XGBoostModel(Model):
    """xgbserver parity: Booster from model.bst / model.json / model.ubj."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._booster = None

    def load(self) -> None:
        try:
            import xgboost as xgb
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'xgboost' requires the xgboost package (absent in "
                "this image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        for fname in ("model.bst", "model.json", "model.ubj"):
            path = self.model_dir / fname
            if path.exists():
                self._booster = xgb.Booster()
                self._booster.load_model(str(path))
                break
        else:
            raise FileNotFoundError(
                f"no model.bst/model.json/model.ubj under {self.model_dir}"
            )
        self.ready = True

    def predict(self, inputs):
        import xgboost as xgb

        return self._booster.predict(
            xgb.DMatrix(np.asarray(inputs))
        ).tolist()


class LightGBMModel(Model):
    """lgbserver parity: Booster from model.txt."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._booster = None

    def load(self) -> None:
        try:
            import lightgbm as lgb
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'lightgbm' requires the lightgbm package (absent in "
                "this image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        path = self.model_dir / "model.txt"
        if not path.exists():
            raise FileNotFoundError(f"no model.txt under {self.model_dir}")
        self._booster = lgb.Booster(model_file=str(path))
        self.ready = True

    def predict(self, inputs):
        return self._booster.predict(np.asarray(inputs)).tolist()


class PaddleModel(Model):
    """paddleserver parity: inference model from model.pdmodel +
    model.pdiparams (gated: paddlepaddle is absent in this image)."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._predictor = None

    def load(self) -> None:
        try:
            import paddle.inference as paddle_infer
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'paddle' requires the paddlepaddle package (absent "
                "in this image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        pdmodel = self.model_dir / "model.pdmodel"
        pdparams = self.model_dir / "model.pdiparams"
        if not pdmodel.exists() or not pdparams.exists():
            raise FileNotFoundError(
                f"no model.pdmodel + model.pdiparams under {self.model_dir}"
            )
        config = paddle_infer.Config(str(pdmodel), str(pdparams))
        self._predictor = paddle_infer.create_predictor(config)
        self.ready = True

    def predict(self, inputs):
        x = np.asarray(inputs, dtype=np.float32)
        names = self._predictor.get_input_names()
        handle = self._predictor.get_input_handle(names[0])
        handle.reshape(x.shape)
        handle.copy_from_cpu(x)
        self._predictor.run()
        out = self._predictor.get_output_handle(
            self._predictor.get_output_names()[0]
        )
        return out.copy_to_cpu().tolist()


class PMMLModel(Model):
    """pmmlserver parity: PMML pipeline via pypmml (gated: absent here)."""

    def __init__(self, name: str, model_dir: str | Path):
        super().__init__(name)
        self.model_dir = Path(model_dir)
        self._model = None

    def load(self) -> None:
        try:
            from pypmml import Model as PmmlModel
        except ModuleNotFoundError as exc:
            raise ModuleNotFoundError(
                "runtime 'pmml' requires the pypmml package (absent in this "
                "image); install it or convert the model to the "
                "sklearn/torch/jax runtime"
            ) from exc
        candidates = sorted(self.model_dir.glob("*.pmml"))
        if not candidates:
            raise FileNotFoundError(f"no *.pmml under {self.model_dir}")
        self._model = PmmlModel.load(str(candidates[0]))
        self.ready = True

    def predict(self, inputs):
        x = np.asarray(inputs)
        return [self._model.predict(list(map(float, row))) for row in x]


RUNTIMES: dict[str, type] = {
    "sklearn": SklearnModel,
    "torch": TorchModel,
    "xgboost": XGBoostModel,
    "lightgbm": LightGBMModel,
    "paddle": PaddleModel,
    "pmml": PMMLModel,
}


def build_runtime(runtime: str, name: str, model_dir: str | Path) -> Model:
    cls = RUNTIMES.get(runtime)
    if cls is None:
        raise ValueError(
            f"unknown runtime {runtime!r} (jax|custom|{'|'.join(RUNTIMES)})"
        )
    return cls(name, model_dir)
