"""ModelServer — REST surface speaking the v1 and v2 inference protocols.

Reference parity (unverified cites, SURVEY.md §2.5, §3.5): kserve
python/kserve/kserve/model_server.py + protocol/ — v1 (`:predict`) and v2
Open Inference Protocol endpoints. Implemented on http.server (stdlib) so
the serving path has zero web-framework dependencies; JSON tensors in/out.

Routes:
  GET  /v2                         server metadata
  GET  /v2/health/live             liveness
  GET  /v2/health/ready            readiness (all models loaded)
  GET  /v2/models/{m}              model metadata
  GET  /v2/models/{m}/ready        per-model readiness
  POST /v2/models/{m}/infer        OIP inference
  GET  /v1/models/{m}              v1 status
  POST /v1/models/{m}:predict      v1 inference ({"instances": [...]})

Run as a pod: python -m kubeflow_tpu.serving.server --model-name m ...
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from kubeflow_tpu.serving.model import Model
from kubeflow_tpu.serving.requestid import (
    get_request_id,
    new_request_id,
    set_request_id,
)

SERVER_NAME = "kubeflow-tpu-modelserver"
SERVER_VERSION = "0.1"

_V2_TO_NP = {
    "FP16": np.float16, "FP32": np.float32, "FP64": np.float64,
    "INT8": np.int8, "INT16": np.int16, "INT32": np.int32, "INT64": np.int64,
    "UINT8": np.uint8, "BOOL": np.bool_,
}
_NP_TO_V2 = {np.dtype(v): k for k, v in _V2_TO_NP.items()}


def _np_to_datatype(arr: np.ndarray) -> str:
    return _NP_TO_V2.get(arr.dtype, "FP32")


class _RawJSON:
    """Pre-serialized JSON response body (single-serialization hot path);
    optionally carries extra response headers (503 Retry-After)."""

    __slots__ = ("data", "headers")

    def __init__(self, data: bytes, headers: dict | None = None):
        self.data = data
        self.headers = headers or {}


class ModelServer:
    """Hosts a repository of models behind one HTTP port.

    Agent capabilities (SURVEY.md §2.5 Agent row — serving/agent.py):
    request/response logging (`request_log_path` + GET /metrics counters),
    adaptive micro-batching (`max_batch_size` > 0 enables; concurrent
    requests coalesce into one forward pass), and the v2 repository API
    (POST /v2/repository/{index,models/{m}/load,models/{m}/unload}) for
    multi-model load/unload against `repository_dir`.
    """

    def __init__(self, models: list[Model] | None = None, port: int = 8080,
                 host: str = "127.0.0.1", request_log_path: str | None = None,
                 max_batch_size: int = 0, batch_max_latency_ms: float = 5.0,
                 repository_dir: str = ""):
        from kubeflow_tpu.serving.agent import MicroBatcher, RequestLogger

        self.models: dict[str, Model] = {}
        self.host = host
        self.port = port
        self.logger = RequestLogger(request_log_path)
        self.max_batch_size = max_batch_size
        self.batch_max_latency_ms = batch_max_latency_ms
        self.repository_dir = repository_dir
        self._batchers: dict[str, MicroBatcher] = {}
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        for m in models or []:
            self.register(m)

    def register(self, model: Model) -> None:
        from kubeflow_tpu.serving.agent import MicroBatcher

        self.models[model.name] = model
        if self.max_batch_size > 0:
            old = self._batchers.pop(model.name, None)
            if old is not None:
                old.stop()
            self._batchers[model.name] = MicroBatcher(
                model, self.max_batch_size, self.batch_max_latency_ms
            )

    def unregister(self, name: str) -> bool:
        b = self._batchers.pop(name, None)
        if b is not None:
            b.stop()
        m = self.models.pop(name, None)
        close = getattr(m, "close", None)
        if close is not None:
            close()  # engine/fleet ticker threads die with the model
        return m is not None

    def _call_model(self, m: Model, arr):
        # dict inputs (multi-input models) cannot coalesce on a shared batch
        # axis — they bypass the adaptive batcher
        batcher = self._batchers.get(m.name)
        if batcher is not None and not isinstance(arr, dict):
            return batcher(arr)
        return m(arr)

    # ----------------------------------------------------------- lifecycle

    def start(self, block: bool = False) -> "ModelServer":
        for m in self.models.values():
            if not m.ready:
                m.load()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        if block:
            self._httpd.serve_forever()
        else:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        for b in self._batchers.values():
            b.stop()
        for m in self.models.values():
            close = getattr(m, "close", None)
            if close is not None:
                close()
        self.logger.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------ handlers

    def handle_get(self, path: str) -> tuple[int, object]:
        if path == "/metrics":
            text = self.logger.render_metrics()  # raw prometheus text
            # continuous-batching engines publish scheduler gauges
            eng_lines = []
            fleet_lines = []
            for name, m in sorted(self.models.items()):
                fleet = getattr(m, "_fleet", None)
                engines = ([(name, getattr(m, "_engine", None))]
                           if fleet is None else
                           [(f"{name}:{r.name}", r.engine)
                            for r in fleet.replicas])
                if fleet is not None:
                    snap = fleet.snapshot()
                    fleet_lines += [
                        f'kfserving_fleet_{k}{{model="{name}"}} {v}'
                        for k, v in sorted(snap.items())
                        if isinstance(v, (int, float))
                    ]
                for label, eng in engines:
                    if eng is None:
                        continue
                    # gauges are instantaneous best-effort reads: the
                    # ticker mutates _rows/step_count OUTSIDE the engine
                    # lock by design (the lock guards only the submit
                    # queue — see tick()'s locking note), so only _queue
                    # needs the lock; a mid-tick read can be off by one
                    # row/dispatch, which a scrape-interval consumer
                    # cannot observe
                    busy = sum(1 for r in eng._rows if r is not None)
                    dispatches = eng.step_count
                    with eng._lock:
                        queued = len(eng._queue)
                    eng_lines += [
                        f'kfserving_engine_decode_dispatches_total'
                        f'{{model="{label}"}} {dispatches}',
                        f'kfserving_engine_rows_busy{{model="{label}"}} '
                        f'{busy}',
                        f'kfserving_engine_rows_total{{model="{label}"}} '
                        f'{eng.max_rows}',
                        f'kfserving_engine_queue_depth{{model="{label}"}} '
                        f'{queued}',
                    ]
            if fleet_lines:
                text += "# TYPE kfserving_fleet gauge\n" \
                    + "\n".join(fleet_lines) + "\n"
            if eng_lines:
                text += "\n".join(
                    ["# TYPE kfserving_engine_decode_dispatches_total "
                     "counter",
                     "# TYPE kfserving_engine_rows_busy gauge",
                     "# TYPE kfserving_engine_rows_total gauge",
                     "# TYPE kfserving_engine_queue_depth gauge"]
                    + eng_lines) + "\n"
            return 200, text
        if path == "/v2":
            return 200, {
                "name": SERVER_NAME,
                "version": SERVER_VERSION,
                "extensions": [],
            }
        if path == "/v2/health/live":
            return 200, {"live": True}
        if path == "/v2/health/ready":
            ready = all(m.ready for m in self.models.values()) and bool(self.models)
            return (200 if ready else 503), {"ready": ready}
        if path.startswith("/v2/models/") and path.endswith("/ready"):
            name = path[len("/v2/models/"):-len("/ready")]
            m = self.models.get(name)
            if m is None:
                return 404, {"error": f"model {name!r} not found"}
            return (200 if m.ready else 503), {"name": name, "ready": m.ready}
        if path.startswith("/v2/models/"):
            name = path[len("/v2/models/"):]
            m = self.models.get(name)
            if m is None:
                return 404, {"error": f"model {name!r} not found"}
            meta = {"name": name, "platform": "jax-xla", "versions": ["1"]}
            im = self.input_metadata(m)
            if im is not None:
                meta["inputs"] = [im]
            return 200, meta
        if path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):]
            m = self.models.get(name)
            if m is None:
                return 404, {"error": f"model {name!r} not found"}
            return 200, {"name": name, "ready": m.ready}
        return 404, {"error": f"no route {path!r}"}

    def handle_post(self, path: str, body: dict, req_bytes: int = 0) -> tuple[int, dict]:
        if path.startswith("/v1/models/") and path.endswith(":predict"):
            name = path[len("/v1/models/"):-len(":predict")]
            return self._logged(name, "v1", req_bytes, self._predict_v1, body)
        if path.startswith("/v1/models/") and path.endswith(":explain"):
            name = path[len("/v1/models/"):-len(":explain")]
            return self._logged(name, "v1-explain", req_bytes,
                                self._explain_v1, body)
        if path.startswith("/v2/models/") and path.endswith("/infer"):
            name = path[len("/v2/models/"):-len("/infer")]
            return self._logged(name, "v2", req_bytes, self._infer_v2, body)
        # ---- v2 repository API (multi-model load/unload)
        if path == "/v2/repository/index":
            return 200, [
                {"name": n, "state": "READY" if m.ready else "UNAVAILABLE",
                 "version": "1"}
                for n, m in sorted(self.models.items())
            ]
        if path.startswith("/v2/repository/models/") and path.endswith("/load"):
            name = path[len("/v2/repository/models/"):-len("/load")]
            return self._repo_load(name, body)
        if path.startswith("/v2/repository/models/") and path.endswith("/unload"):
            name = path[len("/v2/repository/models/"):-len("/unload")]
            if not self.unregister(name):
                return 404, {"error": f"model {name!r} not found"}
            return 200, {"name": name, "state": "UNAVAILABLE"}
        return 404, {"error": f"no route {path!r}"}

    def _logged(self, name: str, protocol: str, req_bytes: int, fn, body):
        import time as _time

        t0 = _time.perf_counter()
        out = fn(name, body)
        # handlers return (code, payload) or (code, payload, headers) —
        # the fleet's 503 shed carries its Retry-After hint through here
        code, payload = out[0], out[1]
        headers = out[2] if len(out) > 2 else None
        # error bodies carry the request id (the apiserver's existing
        # contract, extended to the model server): a logged 4xx/5xx —
        # including the fleet's 503 shed — is greppable back to its
        # X-Request-Id without the client having kept the header
        rid = get_request_id()
        if code >= 400 and isinstance(payload, dict) and rid:
            payload.setdefault("request_id", rid)
        # serialize exactly once: the handler sends these bytes verbatim
        data = json.dumps(payload).encode()
        self.logger.log(
            name, protocol, code, _time.perf_counter() - t0, req_bytes, len(data)
        )
        return code, _RawJSON(data, headers)

    def _repo_load(self, name: str, body: dict) -> tuple[int, dict]:
        """Load (or reload) a model from the repository dir or a storage URI
        — the kserve agent multi-model-puller analogue."""
        import re

        from kubeflow_tpu.serving.model import JaxModel
        from kubeflow_tpu.serving.storage import pull_model

        # the name becomes a filesystem path component: allowlist it so a
        # crafted '../..' name can never escape the repository dir (pull_model
        # rmtree's its destination)
        if not re.fullmatch(r"[A-Za-z0-9][A-Za-z0-9._-]*", name):
            return 422, {"error": f"invalid model name {name!r}"}
        body = body or {}
        uri = body.get("storage_uri", "")
        try:
            if uri:
                model_dir = pull_model(
                    uri, f"{self.repository_dir or '.kubeflow_tpu/models'}/{name}"
                )
            elif self.repository_dir:
                model_dir = f"{self.repository_dir}/{name}"
            else:
                return 400, {"error": "no storage_uri and no repository_dir"}
            model = JaxModel(name, model_dir)
            model.load()
        except Exception as exc:  # noqa: BLE001 — load failure is a client-visible error
            return 500, {"error": f"{type(exc).__name__}: {exc}"}
        self.register(model)
        return 200, {"name": name, "state": "READY"}

    @staticmethod
    def postprocess_arrays(out) -> list[tuple[str, np.ndarray]]:
        """Normalize a model's output into named v2 tensors — the ONE place
        both the HTTP and gRPC v2 surfaces get their output contract from."""
        if isinstance(out, dict):
            # the classification postprocess contract is exactly
            # {predictions[, logits]}; any other key set is a generic
            # named-output model (e.g. triton multi-output) and every
            # tensor must survive
            if "predictions" in out and set(out) <= {"predictions", "logits"}:
                return [
                    ("predictions", np.asarray(out["predictions"])),
                    ("logits",
                     np.asarray(out.get("logits", []), dtype=np.float32)),
                ]
            return [(str(k), np.asarray(v)) for k, v in out.items()]
        return [("output-0", np.asarray(out))]

    @staticmethod
    def input_metadata(m: Model) -> dict | None:
        """v2 metadata for a model's input tensor (shared HTTP/gRPC)."""
        cfg = getattr(m, "config", None)
        if not cfg:
            return None
        return {
            "name": "input-0",
            "datatype": _NP_TO_V2.get(np.dtype(cfg["input_dtype"]), "FP32"),
            "shape": [-1, *cfg["input_shape"][1:]],
        }

    @staticmethod
    def _shed_body(exc) -> dict:
        """The 503 shed response body: error + the shed decision's span
        context and request id when tracing stamped them
        (serving/fleet/router.FleetOverloaded)."""
        body = {"error": str(exc)}
        ctx = getattr(exc, "trace_ctx", None)
        if ctx is not None:
            body["trace"] = ctx.to_header()
        rid = getattr(exc, "request_id", "") or get_request_id()
        if rid:
            body["request_id"] = rid
        return body

    def _get_ready_model(self, name: str) -> Model | tuple[int, dict]:
        m = self.models.get(name)
        if m is None:
            return 404, {"error": f"model {name!r} not found"}
        if not m.ready:
            return 503, {"error": f"model {name!r} not ready"}
        return m

    def _predict_v1(self, name: str, body: dict) -> tuple:
        from kubeflow_tpu.serving.fleet import FleetOverloaded

        m = self._get_ready_model(name)
        if isinstance(m, tuple):
            return m
        instances = body.get("instances")
        if instances is None:
            return 400, {"error": "v1 request must carry 'instances'"}
        timing = None
        try:
            if getattr(m, "_engine", None) is not None \
                    or getattr(m, "_fleet", None) is not None:
                # engine/fleet decode: thread the streaming timing
                # (TTFT, tokens/sec) into the response so clients see
                # engine truth, not HTTP wall-time guesses
                raw, timing = m.predict_timed(
                    m.preprocess(np.asarray(instances)))
                out = m.postprocess(raw)
            else:
                out = self._call_model(m, np.asarray(instances))
        except FleetOverloaded as exc:
            # the activator's existing shed contract: the client re-dials
            # after the hint (serving/client.py _post). The body carries
            # the shed decision's span context, so a shed request is
            # attributable in the trace, not just gone
            return 503, self._shed_body(exc), {
                "Retry-After": str(max(1, int(round(exc.retry_after_s))))}
        except Exception as exc:  # noqa: BLE001 — surface as 500, keep serving
            return 500, {"error": f"{type(exc).__name__}: {exc}"}
        if isinstance(out, dict):
            # ndarray values (multi-output runtimes) must be JSON-ready
            body = {k: v.tolist() if isinstance(v, np.ndarray) else v
                    for k, v in out.items()}
            if "predictions" not in body:
                body = {"predictions": body}
        else:
            body = {"predictions": np.asarray(out).tolist()}
        if timing is not None:
            body["timing"] = timing
        return 200, body

    def _explain_v1(self, name: str, body: dict) -> tuple[int, dict]:
        m = self._get_ready_model(name)
        if isinstance(m, tuple):
            return m
        # no-explainer is a routing fact, decided by type — a crashing
        # explainer (incl. a NotImplementedError from user code) is a 500
        if type(m).explain is Model.explain:
            return 404, {"error": f"model {name!r} has no explainer"}
        instances = body.get("instances")
        if instances is None:
            return 400, {"error": "v1 request must carry 'instances'"}
        try:
            out = m.explain(np.asarray(instances))
        except Exception as exc:  # noqa: BLE001 — surface as 500, keep serving
            return 500, {"error": f"{type(exc).__name__}: {exc}"}
        if isinstance(out, dict):
            return 200, out
        return 200, {"explanations": np.asarray(out).tolist()}

    def _infer_v2(self, name: str, body: dict) -> tuple:
        from kubeflow_tpu.serving.fleet import FleetOverloaded

        m = self._get_ready_model(name)
        if isinstance(m, tuple):
            return m
        inputs = body.get("inputs") or []
        if not inputs:
            return 400, {"error": "v2 request must carry 'inputs'"}

        def decode(t: dict) -> np.ndarray:
            return np.asarray(
                t["data"],
                dtype=_V2_TO_NP.get(t.get("datatype", "FP32"), np.float32),
            ).reshape(t["shape"])

        try:
            if len(inputs) == 1:
                arr = decode(inputs[0])
            else:  # multi-input model: route by declared tensor names
                arr = {t.get("name", f"input-{i}"): decode(t)
                       for i, t in enumerate(inputs)}
            out = self._call_model(m, arr)
        except FleetOverloaded as exc:
            # same shed contract as v1: clients back off on the server's
            # schedule instead of hard-failing or piling on immediately
            return 503, self._shed_body(exc), {
                "Retry-After": str(max(1, int(round(exc.retry_after_s))))}
        except Exception as exc:  # noqa: BLE001
            return 500, {"error": f"{type(exc).__name__}: {exc}"}
        arrays = self.postprocess_arrays(out)
        return 200, {
            "model_name": name,
            "model_version": "1",
            "outputs": [
                {
                    "name": k,
                    "shape": list(v.shape),
                    "datatype": _np_to_datatype(v),
                    "data": v.ravel().tolist(),
                }
                for k, v in arrays
            ],
        }


def _make_handler(server: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to stdout for pod logs
            print(f"[http] {fmt % args}", flush=True)

        def _assign_request_id(self) -> None:
            # assign-or-echo (the apiserver's control-plane contract,
            # extended end-to-end through the serving path): the id
            # rides a contextvar on this request thread so the fleet's
            # `request` root span and every error body can stamp it
            set_request_id(self.headers.get("X-Request-Id")
                           or new_request_id())

        def _reply(self, code: int, payload) -> None:
            extra = {}
            if isinstance(payload, _RawJSON):
                data, ctype = payload.data, "application/json"
                extra = payload.headers
            elif isinstance(payload, str):
                data, ctype = payload.encode(), "text/plain; version=0.0.4"
            else:
                if code >= 400 and isinstance(payload, dict) \
                        and get_request_id():
                    payload.setdefault("request_id", get_request_id())
                data, ctype = json.dumps(payload).encode(), "application/json"
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            if get_request_id():
                self.send_header("X-Request-Id", get_request_id())
            for name, value in extra.items():
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (http.server API)
            self._assign_request_id()
            code, payload = server.handle_get(self.path)
            self._reply(code, payload)

        def do_POST(self):  # noqa: N802
            self._assign_request_id()
            length = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as exc:
                self._reply(400, {"error": f"bad json: {exc}"})
                return
            code, payload = server.handle_post(self.path, body, req_bytes=length)
            self._reply(code, payload)

    return Handler


# -------------------------------------------------------------------- main

def main(argv: list[str] | None = None) -> None:
    import argparse

    from kubeflow_tpu.serving.model import JaxModel, load_model_class
    from kubeflow_tpu.serving.storage import pull_model

    ap = argparse.ArgumentParser(description="kubeflow-tpu model server")
    ap.add_argument("--model-name", required=True)
    ap.add_argument("--storage-uri", default="")
    ap.add_argument("--model-dir", default=".kubeflow_tpu/models")
    ap.add_argument(
        "--runtime", default="jax",
        choices=["jax", "custom", "sklearn", "torch", "xgboost", "lightgbm",
                 "paddle", "pmml", "triton"],
    )
    ap.add_argument("--model-class", default="")
    ap.add_argument("--transformer-class", default="")
    ap.add_argument("--explainer-class", default="")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--device", default="", help="tpu|cpu (default: env)")
    ap.add_argument("--aot", action="store_true",
                    help="jax runtime: export+serialize the compiled "
                         "predictor at load if no artifact exists; replicas "
                         "then serve the AOT artifact (serving/aot.py)")
    # agent features (SURVEY.md §2.5 Agent row)
    ap.add_argument("--request-log", default="",
                    help="JSONL request/response log path")
    ap.add_argument("--max-batch-size", type=int, default=0,
                    help=">0 enables adaptive micro-batching")
    ap.add_argument("--batch-max-latency-ms", type=float, default=5.0)
    ap.add_argument("--repository-dir", default="",
                    help="multi-model repository root for /v2/repository API")
    ap.add_argument("--grpc-port", type=int, default=-1,
                    help=">=0 also serves the v2 OIP over gRPC (0 = ephemeral)")
    args = ap.parse_args(argv)

    if args.device:
        from kubeflow_tpu.utils.device import select_device

        select_device(args.device)

    if os.environ.get("KFT_COMPILE_CACHE"):
        # persistent XLA compile cache (serving/aot.py): pointed at the
        # cache the deploy step warmed, an AOT cold start compiles nothing
        from kubeflow_tpu.serving.aot import _compile_cache_on

        _compile_cache_on(os.environ["KFT_COMPILE_CACHE"])

    if args.runtime == "custom":
        cls = load_model_class(args.model_class)
        model: Model = cls(args.model_name)
    else:
        model_dir = args.model_dir
        if args.storage_uri:
            model_dir = pull_model(args.storage_uri, f"{args.model_dir}/{args.model_name}")
        if args.runtime == "jax":
            if args.aot:
                from kubeflow_tpu.serving.aot import aot_available, export_predictor

                if not aot_available(model_dir):
                    export_predictor(
                        model_dir,
                        compile_cache=os.environ.get("KFT_COMPILE_CACHE") or None,
                    )
            model = JaxModel(args.model_name, model_dir)
        else:
            from kubeflow_tpu.serving.runtimes import build_runtime

            model = build_runtime(args.runtime, args.model_name, model_dir)
    if args.transformer_class:
        from kubeflow_tpu.serving.model import TransformedModel

        t_cls = load_model_class(args.transformer_class)
        model = TransformedModel(
            args.model_name, model, t_cls(f"{args.model_name}-transformer")
        )
    if args.explainer_class:
        from kubeflow_tpu.serving.model import ExplainedModel

        e_cls = load_model_class(args.explainer_class)
        model = ExplainedModel(
            args.model_name, model, e_cls(f"{args.model_name}-explainer")
        )

    srv = ModelServer(
        [model], port=args.port, host=args.host,
        request_log_path=args.request_log or None,
        max_batch_size=args.max_batch_size,
        batch_max_latency_ms=args.batch_max_latency_ms,
        repository_dir=args.repository_dir,
    )
    # gRPC binds BEFORE the HTTP server goes live: the controller's
    # readiness probe is HTTP, and an annotated gRPC port must never refuse
    # connections after readiness reports true
    grpc_note = ""
    if args.grpc_port >= 0:
        from kubeflow_tpu.serving.grpc_server import serve_grpc

        _, grpc_addr = serve_grpc(srv, port=args.grpc_port, host=args.host)
        grpc_note = f" grpc={grpc_addr}"
    srv.start(block=False)
    print(f"server ready url={srv.url} model={args.model_name}{grpc_note}",
          flush=True)
    threading.Event().wait()  # serve until killed


if __name__ == "__main__":
    main()
