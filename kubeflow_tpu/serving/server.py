"""ModelServer — REST surface speaking the v1 and v2 inference protocols.

Reference parity (unverified cites, SURVEY.md §2.5, §3.5): kserve
python/kserve/kserve/model_server.py + protocol/ — v1 (`:predict`) and v2
Open Inference Protocol endpoints. Implemented on http.server (stdlib) so
the serving path has zero web-framework dependencies; JSON tensors in/out.

Routes:
  GET  /v2                         server metadata
  GET  /v2/health/live             liveness
  GET  /v2/health/ready            readiness (all models loaded)
  GET  /v2/models/{m}              model metadata
  GET  /v2/models/{m}/ready        per-model readiness
  POST /v2/models/{m}/infer        OIP inference
  GET  /v1/models/{m}              v1 status
  POST /v1/models/{m}:predict      v1 inference ({"instances": [...]})

Run as a pod: python -m kubeflow_tpu.serving.server --model-name m ...
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from kubeflow_tpu.serving.model import Model

SERVER_NAME = "kubeflow-tpu-modelserver"
SERVER_VERSION = "0.1"

_V2_TO_NP = {
    "FP16": np.float16, "FP32": np.float32, "FP64": np.float64,
    "INT8": np.int8, "INT16": np.int16, "INT32": np.int32, "INT64": np.int64,
    "UINT8": np.uint8, "BOOL": np.bool_,
}
_NP_TO_V2 = {np.dtype(v): k for k, v in _V2_TO_NP.items()}


def _np_to_datatype(arr: np.ndarray) -> str:
    return _NP_TO_V2.get(arr.dtype, "FP32")


class ModelServer:
    """Hosts a repository of models behind one HTTP port."""

    def __init__(self, models: list[Model] | None = None, port: int = 8080,
                 host: str = "127.0.0.1"):
        self.models: dict[str, Model] = {m.name: m for m in (models or [])}
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def register(self, model: Model) -> None:
        self.models[model.name] = model

    # ----------------------------------------------------------- lifecycle

    def start(self, block: bool = False) -> "ModelServer":
        for m in self.models.values():
            if not m.ready:
                m.load()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        if block:
            self._httpd.serve_forever()
        else:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------ handlers

    def handle_get(self, path: str) -> tuple[int, dict]:
        if path == "/v2":
            return 200, {
                "name": SERVER_NAME,
                "version": SERVER_VERSION,
                "extensions": [],
            }
        if path == "/v2/health/live":
            return 200, {"live": True}
        if path == "/v2/health/ready":
            ready = all(m.ready for m in self.models.values()) and bool(self.models)
            return (200 if ready else 503), {"ready": ready}
        if path.startswith("/v2/models/") and path.endswith("/ready"):
            name = path[len("/v2/models/"):-len("/ready")]
            m = self.models.get(name)
            if m is None:
                return 404, {"error": f"model {name!r} not found"}
            return (200 if m.ready else 503), {"name": name, "ready": m.ready}
        if path.startswith("/v2/models/"):
            name = path[len("/v2/models/"):]
            m = self.models.get(name)
            if m is None:
                return 404, {"error": f"model {name!r} not found"}
            meta = {"name": name, "platform": "jax-xla", "versions": ["1"]}
            cfg = getattr(m, "config", None)
            if cfg:
                meta["inputs"] = [{
                    "name": "input-0",
                    "datatype": _NP_TO_V2.get(np.dtype(cfg["input_dtype"]), "FP32"),
                    "shape": [-1, *cfg["input_shape"][1:]],
                }]
            return 200, meta
        if path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):]
            m = self.models.get(name)
            if m is None:
                return 404, {"error": f"model {name!r} not found"}
            return 200, {"name": name, "ready": m.ready}
        return 404, {"error": f"no route {path!r}"}

    def handle_post(self, path: str, body: dict) -> tuple[int, dict]:
        if path.startswith("/v1/models/") and path.endswith(":predict"):
            name = path[len("/v1/models/"):-len(":predict")]
            return self._predict_v1(name, body)
        if path.startswith("/v2/models/") and path.endswith("/infer"):
            name = path[len("/v2/models/"):-len("/infer")]
            return self._infer_v2(name, body)
        return 404, {"error": f"no route {path!r}"}

    def _get_ready_model(self, name: str) -> Model | tuple[int, dict]:
        m = self.models.get(name)
        if m is None:
            return 404, {"error": f"model {name!r} not found"}
        if not m.ready:
            return 503, {"error": f"model {name!r} not ready"}
        return m

    def _predict_v1(self, name: str, body: dict) -> tuple[int, dict]:
        m = self._get_ready_model(name)
        if isinstance(m, tuple):
            return m
        instances = body.get("instances")
        if instances is None:
            return 400, {"error": "v1 request must carry 'instances'"}
        try:
            out = m(np.asarray(instances))
        except Exception as exc:  # noqa: BLE001 — surface as 500, keep serving
            return 500, {"error": f"{type(exc).__name__}: {exc}"}
        if isinstance(out, dict) and "predictions" in out:
            return 200, out
        return 200, {"predictions": np.asarray(out).tolist()}

    def _infer_v2(self, name: str, body: dict) -> tuple[int, dict]:
        m = self._get_ready_model(name)
        if isinstance(m, tuple):
            return m
        inputs = body.get("inputs") or []
        if not inputs:
            return 400, {"error": "v2 request must carry 'inputs'"}
        t = inputs[0]
        try:
            arr = np.asarray(
                t["data"], dtype=_V2_TO_NP.get(t.get("datatype", "FP32"), np.float32)
            ).reshape(t["shape"])
            out = m(arr)
        except Exception as exc:  # noqa: BLE001
            return 500, {"error": f"{type(exc).__name__}: {exc}"}
        if isinstance(out, dict):  # classification postprocess contract
            arrays = [
                ("predictions", np.asarray(out["predictions"])),
                ("logits", np.asarray(out.get("logits", []), dtype=np.float32)),
            ]
        else:
            arrays = [("output-0", np.asarray(out))]
        return 200, {
            "model_name": name,
            "model_version": "1",
            "outputs": [
                {
                    "name": k,
                    "shape": list(v.shape),
                    "datatype": _np_to_datatype(v),
                    "data": v.ravel().tolist(),
                }
                for k, v in arrays
            ],
        }


def _make_handler(server: ModelServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to stdout for pod logs
            print(f"[http] {fmt % args}", flush=True)

        def _reply(self, code: int, payload: dict) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (http.server API)
            code, payload = server.handle_get(self.path)
            self._reply(code, payload)

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as exc:
                self._reply(400, {"error": f"bad json: {exc}"})
                return
            code, payload = server.handle_post(self.path, body)
            self._reply(code, payload)

    return Handler


# -------------------------------------------------------------------- main

def main(argv: list[str] | None = None) -> None:
    import argparse

    from kubeflow_tpu.serving.model import JaxModel, load_model_class
    from kubeflow_tpu.serving.storage import pull_model

    ap = argparse.ArgumentParser(description="kubeflow-tpu model server")
    ap.add_argument("--model-name", required=True)
    ap.add_argument("--storage-uri", default="")
    ap.add_argument("--model-dir", default=".kubeflow_tpu/models")
    ap.add_argument("--runtime", default="jax", choices=["jax", "custom"])
    ap.add_argument("--model-class", default="")
    ap.add_argument("--transformer-class", default="")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--device", default="", help="tpu|cpu (default: env)")
    args = ap.parse_args(argv)

    if args.device:
        from kubeflow_tpu.utils.device import select_device

        select_device(args.device)

    if args.runtime == "jax":
        model_dir = args.model_dir
        if args.storage_uri:
            model_dir = pull_model(args.storage_uri, f"{args.model_dir}/{args.model_name}")
        model: Model = JaxModel(args.model_name, model_dir)
    else:
        cls = load_model_class(args.model_class)
        model = cls(args.model_name)
    if args.transformer_class:
        from kubeflow_tpu.serving.model import TransformedModel

        t_cls = load_model_class(args.transformer_class)
        model = TransformedModel(
            args.model_name, model, t_cls(f"{args.model_name}-transformer")
        )

    srv = ModelServer([model], port=args.port, host=args.host)
    srv.start(block=False)
    print(f"server ready url={srv.url} model={args.model_name}", flush=True)
    threading.Event().wait()  # serve until killed


if __name__ == "__main__":
    main()
