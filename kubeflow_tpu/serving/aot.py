"""True XLA-AOT serving (VERDICT r2 missing #2; SURVEY.md §2.5 "a predictor
container that loads an XLA-AOT-compiled model").

Deploy time, `export_predictor(model_dir)`:
  - rebuilds the predictor once, bakes the restored params into the traced
    computation as constants, and serializes the jax.export artifact
    (StableHLO + calling convention) to `predictor.jaxexport` — fully
    self-contained, no flax module / params restore / Python retracing at
    load;
  - optionally pre-warms a persistent XLA compilation cache
    (`compile_cache=`) by compiling the artifact for the CURRENT backend,
    so a serving process pointed at the same cache performs ZERO backend
    compilations on cold start (asserted in tests via the
    /jax/compilation_cache/cache_misses monitoring counter).

Serve time, JaxModel.load() prefers the artifact when its platform matches
the running backend. Batches are padded/chunked to the exported batch size —
the TPU-native fixed-shape serving pattern (static shapes keep XLA from
recompiling per request batch size).
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

AOT_FILE = "predictor.jaxexport"
AOT_META = "aot.json"


def _compile_cache_on(cache_dir: str | Path) -> None:
    # one cache-config path for serving cold-start AND training restart
    # (utils/compile_cache.py) — kept as the module-local name the serving
    # tests and operators already import
    from kubeflow_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache(cache_dir)


def export_predictor(
    model_dir: str | Path,
    compile_cache: str | Path | None = None,
) -> Path:
    """Compile-and-serialize the predictor in `model_dir` (the save_predictor
    layout) for the current backend. Returns the artifact path."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.serving.model import _load_predict_fn

    model_dir = Path(model_dir)
    predict_fn, config, example = _load_predict_fn(model_dir)
    gen = config.get("generate")
    if gen is not None and float(gen.get("temperature", 0.0)) > 0.0:
        raise ValueError(
            "AOT export supports greedy decode only (temperature == 0): "
            "sampling needs a fresh per-request rng, which the single-input "
            "exported artifact cannot receive — serve sampling configs via "
            "the jit path"
        )

    exp = jax.export.export(jax.jit(predict_fn))(
        jax.ShapeDtypeStruct(example.shape, example.dtype)
    )
    (model_dir / AOT_FILE).write_bytes(exp.serialize())
    (model_dir / AOT_META).write_text(json.dumps({
        "platforms": list(exp.platforms),
        "batch_size": int(example.shape[0]),
        "jax_version": jax.__version__,
    }, indent=2))
    if compile_cache is not None:
        # warm the persistent cache with the exact executable a serving
        # process will build from this artifact
        _compile_cache_on(compile_cache)
        loaded = load_exported(model_dir)
        np.asarray(loaded(jnp.asarray(example)))
    return model_dir / AOT_FILE


def aot_available(model_dir: str | Path) -> bool:
    """True when an artifact exists AND targets the running backend."""
    import jax

    model_dir = Path(model_dir)
    if not (model_dir / AOT_FILE).exists() or not (model_dir / AOT_META).exists():
        return False
    meta = json.loads((model_dir / AOT_META).read_text())
    return jax.default_backend() in meta.get("platforms", [])


def load_exported(model_dir: str | Path):
    """Deserialize the artifact -> callable. No flax module, no params
    restore, no Python retrace of model code."""
    import jax

    exp = jax.export.deserialize((Path(model_dir) / AOT_FILE).read_bytes())
    return exp.call


def padded_chunk_predict(call, x: np.ndarray, batch_size: int) -> np.ndarray:
    """Run a fixed-batch exported callable over an arbitrary-length batch:
    chunk to `batch_size`, zero-pad the tail, slice real rows back out."""
    import jax.numpy as jnp

    outs = []
    for i in range(0, x.shape[0], batch_size):
        part = x[i:i + batch_size]
        real = part.shape[0]
        if real < batch_size:
            part = np.concatenate(
                [part, np.zeros((batch_size - real, *part.shape[1:]),
                                part.dtype)]
            )
        outs.append(np.asarray(call(jnp.asarray(part)))[:real])
    return np.concatenate(outs) if len(outs) > 1 else outs[0]
