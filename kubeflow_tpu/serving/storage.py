"""Storage initializer — pulls a model into the pod's model dir.

Reference parity (unverified cites, SURVEY.md §2.5): kserve
python/kserve/kserve/storage/storage.py, which runs as an initContainer and
materializes gs://, s3://, pvc://, hf://, file:// URIs under /mnt/models.

Remote schemes (gs/s3/hf/http) go through an ObjectStore provider:
  - This environment has zero egress, so the default provider raises a
    clear gated error rather than shipping stubbed-but-broken downloads.
  - Setting KFTPU_OBJECT_STORE_EMULATOR=<dir> swaps in a file-backed
    emulator with real object-store semantics — bucket/key-prefix listing,
    per-object fetch, atomic materialization, and a (size, mtime) pull
    cache — so every remote-scheme code path (layout, caching, error
    handling) runs and is tested without egress. Emulator layout:
    <root>/<scheme>/<bucket>/<key...> (e.g. <root>/gs/my-bucket/model/...).

pvc:// resolves under a configurable local volume root (the PVC mount
analogue); file:// and bare paths copy from the local filesystem.
"""

from __future__ import annotations

import json
import os
import shutil
from dataclasses import dataclass
from pathlib import Path
from urllib.parse import urlparse

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.utils.envvars import ENV_OBJECT_STORE_EMULATOR, ENV_PVC_ROOT

# PVC mount root: pvc://volume-name/sub/path -> $KFTPU_PVC_ROOT/volume-name/sub/path
PVC_ROOT_ENV = ENV_PVC_ROOT
DEFAULT_PVC_ROOT = ".kubeflow_tpu/volumes"

# local tree emulating gs://, s3://, hf://, http(s):// object stores
EMULATOR_ENV = ENV_OBJECT_STORE_EMULATOR

_REMOTE_SCHEMES = ("gs", "s3", "hf", "http", "https")
# per-destination pull cache: object key -> (size, mtime) of the fetched copy
MANIFEST_FILE = ".kft_pull_manifest.json"


@dataclass(frozen=True)
class ObjectInfo:
    key: str      # full key within the bucket
    size: int
    mtime: float


class ObjectStore:
    """Minimal object-store surface the initializer needs: prefix listing
    and per-object fetch. Real GCS/S3/HF clients implement the same two
    calls; this environment ships the file-backed emulator only."""

    def list(self, bucket: str, prefix: str) -> list[ObjectInfo]:
        raise NotImplementedError

    def fetch(self, bucket: str, key: str, dest: Path) -> None:
        raise NotImplementedError


class EmulatedObjectStore(ObjectStore):
    """File-backed emulator: <root>/<scheme>/<bucket>/<key...>."""

    def __init__(self, scheme: str, root: Path):
        self.root = Path(root) / scheme

    def _base(self, bucket: str) -> Path:
        # bucket names are single path components; '..', '/', '' would walk
        # out of the emulator tree (the uri is client-controllable via the
        # repository load API)
        if not bucket or "/" in bucket or bucket in (".", ".."):
            raise ValueError(f"invalid bucket name {bucket!r}")
        return self.root / bucket

    def list(self, bucket: str, prefix: str) -> list[ObjectInfo]:
        base = self._base(bucket)
        if not base.is_dir():
            return []
        prefix = prefix.strip("/")
        if ".." in prefix.split("/"):
            raise ValueError(f"invalid key prefix {prefix!r}")
        # walk only the prefix subtree (or the single object), not the
        # whole bucket — listing cost tracks the model, not the store
        start = base / prefix if prefix else base
        if start.is_file():
            candidates = [start]
        elif start.is_dir():
            candidates = sorted(p for p in start.rglob("*") if p.is_file())
        else:
            return []
        out = []
        for p in candidates:
            if p.name == MANIFEST_FILE:
                continue
            key = p.relative_to(base).as_posix()
            st = p.stat()
            out.append(ObjectInfo(key, st.st_size, st.st_mtime))
        return out

    def fetch(self, bucket: str, key: str, dest: Path) -> None:
        src = self._base(bucket) / key
        dest.parent.mkdir(parents=True, exist_ok=True)
        tmp = dest.with_name(dest.name + ".part")
        shutil.copy2(src, tmp)
        tmp.replace(dest)  # atomic: a crashed pull never leaves half files


def _provider_for(scheme: str) -> ObjectStore:
    root = os.environ.get(EMULATOR_ENV)
    if root:
        return EmulatedObjectStore(scheme, Path(root))
    raise RuntimeError(
        f"storage scheme '{scheme}://' needs network egress, which this "
        f"environment does not have; stage the model locally and use "
        f"file:// or pvc:// instead, or point {EMULATOR_ENV} at a "
        f"file-backed emulator tree"
    )


def _split_remote(uri: str) -> tuple[str, str]:
    """'gs://bucket/a/b' -> ('bucket', 'a/b'); hf://org/model keeps the org
    as the bucket; http(s) uses the host."""
    parsed = urlparse(uri)
    return parsed.netloc, parsed.path.strip("/")


def _pull_remote(uri: str, scheme: str, dest: Path) -> Path:
    bucket, prefix = _split_remote(uri)
    if not bucket:
        raise ValueError(f"storage uri {uri!r}: missing bucket/host")
    store = _provider_for(scheme)
    # enforce the key/prefix "directory" boundary HERE, not per-provider:
    # real S3/GCS listings are plain string-prefix matches, so a provider
    # that faithfully mirrors them would otherwise leak 'model2/x' into a
    # pull of 'model'
    objs = [
        o for o in store.list(bucket, prefix)
        if not prefix or o.key == prefix or o.key.startswith(prefix + "/")
    ]
    if not objs:
        raise FileNotFoundError(
            f"storage uri {uri!r}: no objects under bucket {bucket!r} "
            f"prefix {prefix!r}"
        )
    manifest_path = dest / MANIFEST_FILE
    if dest.exists() and not manifest_path.exists():
        # dest was materialized by something other than a remote pull (a
        # local-scheme copy, a stale model): REPLACE, per the idempotence
        # contract — merging would serve mixed model files
        shutil.rmtree(dest)
    dest.mkdir(parents=True, exist_ok=True)
    try:
        manifest = json.loads(manifest_path.read_text())
        if not isinstance(manifest, dict):
            raise ValueError(f"manifest is {type(manifest).__name__}")
        # the cache is only valid for the SAME source: two versions of a
        # model can share sizes+mtimes (cp -p publishing), so a uri switch
        # must refetch everything
        cache = manifest["objects"] if manifest.get("uri") == uri else {}
        if not isinstance(cache, dict):
            cache = {}
    except (OSError, ValueError, TypeError, KeyError):
        cache = {}
    new_cache = {}
    for obj in objs:
        # dest-relative name: strip the shared prefix "directory"
        rel = obj.key
        if prefix and rel == prefix:
            rel = Path(obj.key).name  # single-object uri
        elif prefix:
            rel = obj.key[len(prefix) + 1:]
        entry = [obj.size, obj.mtime]
        target = dest / rel
        if cache.get(rel) == entry and target.exists():
            new_cache[rel] = entry  # unchanged: skip the fetch
            continue
        store.fetch(bucket, obj.key, target)
        new_cache[rel] = entry
    # drop whatever the source does not have NOW — diffed against the dest
    # tree itself, not the previous manifest, so cleanup survives a lost or
    # corrupted manifest
    for p in list(dest.rglob("*")):
        if not p.is_file() or p.name == MANIFEST_FILE:
            continue
        if p.relative_to(dest).as_posix() not in new_cache:
            p.unlink()
    tmp = manifest_path.with_name(manifest_path.name + ".tmp")
    tmp.write_text(json.dumps({"uri": uri, "objects": new_cache}))
    tmp.replace(manifest_path)  # atomic: no torn manifest on crash
    return dest


def _normalize(storage_uri: str) -> tuple[str, str]:
    """One place deciding remote-vs-local: (stripped uri, scheme or '')."""
    uri = storage_uri.strip()
    scheme = urlparse(uri).scheme
    return uri, (scheme if scheme in _REMOTE_SCHEMES else "")


def resolve_uri(storage_uri: str) -> Path:
    """Map a LOCAL storage URI to a source path (no copy). Remote schemes
    have no local source path; pull_model handles them via providers."""
    uri, scheme = _normalize(storage_uri)
    if scheme:
        raise RuntimeError(
            f"storage scheme {scheme + '://'!r} has no local path; use "
            f"pull_model to materialize it"
        )
    if uri.startswith("pvc://"):
        root = Path(os.environ.get(PVC_ROOT_ENV, DEFAULT_PVC_ROOT))
        return root / uri[len("pvc://"):]
    if uri.startswith("file://"):
        return Path(uri[len("file://"):])
    return Path(uri)


# Per-destination locks: the repository API serves concurrent load requests
# from ThreadingHTTPServer threads; two pulls racing into one dest would
# cross rmtree/fetch and tear the tree. In-process is sufficient — replicas
# are separate processes with per-replica dest dirs.
_PULL_LOCKS: dict[str, object] = {}
_PULL_LOCKS_GUARD = make_lock("storage._PULL_LOCKS_GUARD")


def _dest_lock(dest: Path):
    key = str(Path(dest).resolve())
    with _PULL_LOCKS_GUARD:
        lock = _PULL_LOCKS.get(key)
        if lock is None:
            lock = _PULL_LOCKS[key] = make_lock(f"storage._dest_lock[{key}]")
    return lock


def pull_model(storage_uri: str, dest_dir: str | Path) -> Path:
    """Materialize the model under dest_dir (the /mnt/models contract).
    Returns the destination path. Idempotent: re-pull replaces (local
    schemes) or incrementally syncs via the pull cache (remote schemes).
    Serialized per destination — concurrent loads of the same model are
    safe."""
    with _dest_lock(Path(dest_dir)):
        return _pull_model_locked(storage_uri, dest_dir)


def _pull_model_locked(storage_uri: str, dest_dir: str | Path) -> Path:
    uri, scheme = _normalize(storage_uri)
    if scheme:
        return _pull_remote(uri, scheme, Path(dest_dir))
    src = resolve_uri(uri)
    if not src.exists():
        raise FileNotFoundError(f"storage uri {storage_uri!r} -> {src} not found")
    dest = Path(dest_dir)
    if dest.exists():
        shutil.rmtree(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)
    if src.is_dir():
        shutil.copytree(src, dest)
    else:
        dest.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, dest / src.name)
    return dest
