"""Storage initializer — pulls a model into the pod's model dir.

Reference parity (unverified cites, SURVEY.md §2.5): kserve
python/kserve/kserve/storage/storage.py, which runs as an initContainer and
materializes gs://, s3://, pvc://, hf://, file:// URIs under /mnt/models.
This environment has zero egress, so the remote schemes are gated with a
clear error instead of stubbed-but-broken downloads; pvc:// resolves under a
configurable local volume root (the PVC mount analogue).
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path

# PVC mount root: pvc://volume-name/sub/path -> $KFTPU_PVC_ROOT/volume-name/sub/path
PVC_ROOT_ENV = "KFTPU_PVC_ROOT"
DEFAULT_PVC_ROOT = ".kubeflow_tpu/volumes"

_REMOTE_SCHEMES = ("gs://", "s3://", "hf://", "http://", "https://")


def resolve_uri(storage_uri: str) -> Path:
    """Map a storage URI to a local source path (no copy)."""
    uri = storage_uri.strip()
    for scheme in _REMOTE_SCHEMES:
        if uri.startswith(scheme):
            raise RuntimeError(
                f"storage scheme {scheme!r} needs network egress, which this "
                f"environment does not have; stage the model locally and use "
                f"file:// or pvc:// instead"
            )
    if uri.startswith("pvc://"):
        root = Path(os.environ.get(PVC_ROOT_ENV, DEFAULT_PVC_ROOT))
        return root / uri[len("pvc://"):]
    if uri.startswith("file://"):
        return Path(uri[len("file://"):])
    return Path(uri)


def pull_model(storage_uri: str, dest_dir: str | Path) -> Path:
    """Materialize the model under dest_dir (the /mnt/models contract).
    Returns the destination path. Idempotent: re-pull replaces."""
    src = resolve_uri(storage_uri)
    if not src.exists():
        raise FileNotFoundError(f"storage uri {storage_uri!r} -> {src} not found")
    dest = Path(dest_dir)
    if dest.exists():
        shutil.rmtree(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)
    if src.is_dir():
        shutil.copytree(src, dest)
    else:
        dest.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, dest / src.name)
    return dest
