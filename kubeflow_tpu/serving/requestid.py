"""X-Request-Id propagation for the serving data plane.

The apiserver has assigned/echoed X-Request-Id on control-plane requests
since PR 2; this module gives the MODEL server the same contract without
threading a parameter through every Model method: the HTTP handler
assigns (or echoes) the id and parks it in a contextvar, and anything
downstream on the same request thread — the fleet router's `request`
root span, error bodies, the 503 shed response — reads it back. Handler
threads are per-request (ThreadingHTTPServer), so the contextvar can
never leak across concurrent requests.
"""

from __future__ import annotations

import contextvars
import uuid

_REQUEST_ID: contextvars.ContextVar = contextvars.ContextVar(
    "serving_request_id", default="")


def new_request_id() -> str:
    """A fresh id in the apiserver's shape (16 hex chars)."""
    return uuid.uuid4().hex[:16]


def set_request_id(rid: str) -> None:
    _REQUEST_ID.set(rid or "")


def get_request_id() -> str:
    """The current request's id ("" outside a serving request)."""
    return _REQUEST_ID.get()
