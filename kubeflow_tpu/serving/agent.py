"""Serving agent capabilities: request logging + adaptive micro-batching.

Reference parity (unverified cites, SURVEY.md §2.5 Agent row): the kserve Go
agent sidecar provides request/response logging, batching, and multi-model
pulling. Here they are in-process features of the model server — there is no
sidecar boundary to cross, and micro-batching in particular belongs next to
the model: concatenating concurrent requests into one forward pass is THE
TPU throughput lever (a bigger batch keeps the MXU fed; per-request calls
leave it idle between dispatches).

The multi-model repository API lives in server.py (/v2/repository/*).
"""

from __future__ import annotations

import json
import threading
import time

from kubeflow_tpu.analysis.lockcheck import make_lock
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np


class RequestLogger:
    """JSONL request/response log + Prometheus-style counters.

    One line per request: ts, model, protocol, code, latency_ms, and
    request/response byte sizes — the kserve logger's CloudEvents payload
    collapsed to its queryable core.
    """

    def __init__(self, path: str | None = None):
        self.path = Path(path) if path else None
        self._mu = make_lock("agent.RequestLogger._mu")
        self._fh = None
        if self.path is not None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        # (model, protocol, code) -> count; model -> (latency_sum_s, count)
        self.requests_total: dict[tuple[str, str, int], int] = {}
        self.latency: dict[str, list[float]] = {}
        # per-model latency histogram buckets (serving SLOs live in the
        # tail, which a sum/count summary cannot show)
        self.latency_buckets: tuple[float, ...] = (
            0.005, 0.025, 0.1, 0.25, 1.0, 2.5, 10.0)
        self.latency_hist: dict[str, list[int]] = {}

    def log(self, model: str, protocol: str, code: int, latency_s: float,
            req_bytes: int, resp_bytes: int) -> None:
        with self._mu:
            key = (model, protocol, code)
            self.requests_total[key] = self.requests_total.get(key, 0) + 1
            agg = self.latency.setdefault(model, [0.0, 0])
            agg[0] += latency_s
            agg[1] += 1
            from kubeflow_tpu.utils.prom import observe

            hist = self.latency_hist.setdefault(
                model, [0] * (len(self.latency_buckets) + 1))
            observe(self.latency_buckets, hist, latency_s)
            if self._fh is not None:
                self._fh.write(json.dumps({
                    "ts": time.time(),
                    "model": model,
                    "protocol": protocol,
                    "code": code,
                    "latency_ms": round(latency_s * 1e3, 3),
                    "request_bytes": req_bytes,
                    "response_bytes": resp_bytes,
                }) + "\n")
                self._fh.flush()

    def render_metrics(self) -> str:
        with self._mu:
            lines = [
                "# TYPE kfserving_requests_total counter",
            ]
            for (model, proto, code), n in sorted(self.requests_total.items()):
                lines.append(
                    f'kfserving_requests_total{{model="{model}",'
                    f'protocol="{proto}",code="{code}"}} {n}'
                )
            from kubeflow_tpu.utils.prom import render_histogram

            lines.append("# TYPE kfserving_request_latency_seconds histogram")
            for model, (s, n) in sorted(self.latency.items()):
                render_histogram(
                    lines, "kfserving_request_latency_seconds",
                    self.latency_buckets,
                    self.latency_hist.get(
                        model, [0] * (len(self.latency_buckets) + 1)),
                    s, labels=f'model="{model}",', emit_type=False,
                )
            return "\n".join(lines) + "\n"

    def close(self) -> None:
        with self._mu:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


@dataclass
class _Pending:
    arr: np.ndarray
    event: threading.Event = field(default_factory=threading.Event)
    result: object = None
    error: BaseException | None = None


class MicroBatcher:
    """Adaptive micro-batching around one model.

    Concurrent requests queue up; a worker flushes when either
    `max_batch_size` rows are waiting or the oldest request has waited
    `max_latency_ms` — the same knobs as the kserve agent batcher. Requests
    are concatenated on the leading (batch) dim, run as ONE forward pass,
    and the outputs are split back per request.
    """

    def __init__(self, model, max_batch_size: int = 32,
                 max_latency_ms: float = 5.0, timeout_s: float = 60.0):
        self.model = model
        self.max_batch_size = max_batch_size
        self.max_latency_s = max_latency_ms / 1e3
        self.timeout_s = timeout_s
        self.batches_run = 0
        self.requests_batched = 0
        self._q: deque[_Pending] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._worker = threading.Thread(
            target=self._loop, name=f"batcher-{getattr(model, 'name', '?')}",
            daemon=True,
        )
        self._worker.start()

    # --------------------------------------------------------------- client

    def __call__(self, arr: np.ndarray):
        # 0-d input would crash the shared worker (len() of unsized object)
        # — normalize here so one bad request can never kill the batcher
        p = _Pending(arr=np.atleast_1d(np.asarray(arr)))
        with self._cv:
            if self._stop:
                raise RuntimeError("batcher stopped")
            self._q.append(p)
            self._cv.notify()
        if not p.event.wait(self.timeout_s):
            raise TimeoutError("batched predict timed out")
        if p.error is not None:
            raise p.error
        return p.result

    # --------------------------------------------------------------- worker

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop and not self._q:
                    return  # drained: in-flight requests flushed before exit
                if not self._stop:
                    deadline = time.monotonic() + self.max_latency_s
                    while (
                        sum(len(p.arr) for p in self._q) < self.max_batch_size
                        and time.monotonic() < deadline
                    ):
                        self._cv.wait(
                            timeout=max(deadline - time.monotonic(), 0.001)
                        )
                items: list[_Pending] = []
                rows = 0
                while self._q and rows < self.max_batch_size:
                    items.append(self._q.popleft())
                    rows += len(items[-1].arr)
            try:
                self._run(items)
            except BaseException:  # noqa: BLE001 — the worker must not die
                for p in items:
                    p.event.set()

    def _run(self, items: list[_Pending]) -> None:
        try:
            batch = np.concatenate([p.arr for p in items], axis=0)
            out = self.model(batch)
            offsets = np.cumsum([0] + [len(p.arr) for p in items])
            for i, p in enumerate(items):
                lo, hi = offsets[i], offsets[i + 1]
                if isinstance(out, dict):
                    p.result = {
                        k: np.asarray(v)[lo:hi] for k, v in out.items()
                    }
                else:
                    p.result = np.asarray(out)[lo:hi]
        except BaseException as exc:  # noqa: BLE001 — deliver to every waiter
            for p in items:
                p.error = exc
        finally:
            self.batches_run += 1
            self.requests_batched += len(items)
            for p in items:
                p.event.set()

    def stop(self) -> None:
        """Stop after draining: queued requests are flushed through the
        model, not abandoned to their timeouts."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout=10.0)
