"""gRPC v2 Open Inference Protocol — the kserve GRPCInferenceService shape.

Reference parity (SURVEY.md §2.5 model-server row): kserve's ModelServer
serves v2 over BOTH REST and gRPC (python/kserve/kserve/protocol/grpc).
Here the gRPC surface wraps the SAME ModelServer instance the HTTP handler
uses — one model registry, one micro-batcher, one request logger — so the
two protocols can never disagree about readiness or model state.

Wire details follow the public grpc_predict_v2.proto exactly — package and
service name (`/inference.GRPCInferenceService/...`), nested tensor
messages, public field numbers, typed flat contents AND triton-style
raw_input_contents — so a generic OIP gRPC client interoperates (ADVICE r2:
a private package/renumbered fields broke that; fixed). Wiring uses
`method_handlers_generic_handler` like sweep/rpc.py (no grpc_tools codegen
plugin in this image).
"""

from __future__ import annotations

import time as _time
from concurrent import futures

import grpc
import numpy as np

from kubeflow_tpu.protos import inference_pb2 as pb

INFERENCE_SERVICE = "inference.GRPCInferenceService"

# OIP datatype -> (numpy dtype, typed contents field). The dtype SET is
# derived from the HTTP handler's _V2_TO_NP so the two protocols accept the
# same datatypes by construction; only the wire field differs per kind.
# Narrow ints ride the widest typed field of their kind; FP16 values travel
# in fp32_contents (proto has no fp16 field; precision is preserved).
from kubeflow_tpu.serving.server import _V2_TO_NP as _HTTP_DT  # noqa: E402


def _contents_field(np_dtype) -> str:
    kind = np.dtype(np_dtype).kind
    sz = np.dtype(np_dtype).itemsize
    return {
        "b": "bool_contents",
        "i": "int64_contents" if sz == 8 else "int_contents",
        "u": "uint64_contents" if sz == 8 else "uint_contents",
        "f": "fp64_contents" if sz == 8 else "fp32_contents",
    }[kind]


_DT = {name: (dt, _contents_field(dt)) for name, dt in _HTTP_DT.items()}
_DT["UINT32"] = (np.uint32, "uint_contents")
_NP_TO_DT = {np.dtype(v[0]): k for k, v in _DT.items()}


def _to_array(t: pb.ModelInferRequest.InferInputTensor,
              raw: bytes | None = None) -> np.ndarray:
    dt, field = _DT[t.datatype]  # caller validates membership + count first
    if raw is not None:  # triton-style raw little-endian payload
        return np.frombuffer(raw, dtype=np.dtype(dt).newbyteorder("<")) \
            .astype(dt).reshape(tuple(t.shape))
    data = getattr(t.contents, field)
    return np.asarray(data, dtype=dt).reshape(tuple(t.shape))


def _resolve_dtype(arr) -> tuple[np.ndarray, str]:
    """One wire-dtype decision for typed AND raw responses: bf16 / f16 and
    friends travel as FP32."""
    arr = np.asarray(arr)
    dtype = _NP_TO_DT.get(arr.dtype)
    if dtype is None:
        arr, dtype = arr.astype(np.float32), "FP32"
    return arr, dtype


def _to_tensor(name: str, arr: np.ndarray) -> pb.ModelInferResponse.InferOutputTensor:
    arr, dtype = _resolve_dtype(arr)
    out = pb.ModelInferResponse.InferOutputTensor(
        name=name, datatype=dtype, shape=list(arr.shape))
    getattr(out.contents, _DT[dtype][1]).extend(arr.ravel().tolist())
    return out


class InferenceGrpcService:
    """The five OIP rpcs over a live ModelServer's registry."""

    def __init__(self, model_server):
        self.ms = model_server

    def ServerLive(self, req, ctx):
        return pb.ServerLiveResponse(live=True)

    def ServerReady(self, req, ctx):
        models = self.ms.models
        ready = bool(models) and all(m.ready for m in models.values())
        return pb.ServerReadyResponse(ready=ready)

    def ModelReady(self, req, ctx):
        m = self.ms.models.get(req.name)
        if m is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND, f"model {req.name!r} not found")
        return pb.ModelReadyResponse(ready=m.ready)

    def ModelMetadata(self, req, ctx):
        m = self.ms.models.get(req.name)
        if m is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND, f"model {req.name!r} not found")
        resp = pb.ModelMetadataResponse(
            name=req.name, versions=["1"], platform="jax-xla"
        )
        im = self.ms.input_metadata(m)  # shared with HTTP v2
        if im is not None:
            resp.inputs.append(pb.ModelMetadataResponse.TensorMetadata(
                name=im["name"], datatype=im["datatype"], shape=im["shape"]
            ))
        return resp

    def ModelInfer(self, req, ctx):
        name = req.model_name
        m = self.ms.models.get(name)
        if m is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND, f"model {name!r} not found")
        if not m.ready:
            ctx.abort(grpc.StatusCode.UNAVAILABLE, f"model {name!r} not ready")
        if not req.inputs:
            ctx.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "request must carry at least one input tensor",
            )
        if req.raw_input_contents and \
                len(req.raw_input_contents) != len(req.inputs):
            ctx.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"raw_input_contents carries {len(req.raw_input_contents)} "
                f"blobs for {len(req.inputs)} inputs (all-raw or all-typed)",
            )
        raw0 = req.raw_input_contents[0] if req.raw_input_contents else None
        decoded: list[np.ndarray] = []
        for i, t in enumerate(req.inputs):
            if t.datatype not in _DT:
                ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"unsupported datatype {t.datatype!r} "
                    f"(supported: {sorted(_DT)})",
                )
            want = 1
            for d in t.shape:
                want *= d
            raw = req.raw_input_contents[i] if req.raw_input_contents else None
            if raw is not None:
                itemsize = np.dtype(_DT[t.datatype][0]).itemsize
                if len(raw) != want * itemsize:
                    ctx.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        f"raw_input_contents[{i}] carries {len(raw)} bytes "
                        f"but shape {list(t.shape)} x {t.datatype} needs "
                        f"{want * itemsize}",
                    )
            else:
                field = _DT[t.datatype][1]
                got = len(getattr(t.contents, field))
                if got != want:
                    ctx.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        f"{field} carries {got} elements but shape "
                        f"{list(t.shape)} needs {want}",
                    )
            decoded.append(_to_array(t, raw))
        t0 = _time.perf_counter()
        try:
            if len(decoded) == 1:
                arr = decoded[0]
            else:  # multi-input model: route by declared tensor names
                arr = {t.name or f"input-{i}": a
                       for i, (t, a) in enumerate(zip(req.inputs, decoded))}
            out = self.ms._call_model(m, arr)
        except Exception as exc:  # noqa: BLE001 — surface as INTERNAL, not a crash
            self.ms.logger.log(name, "v2-grpc", 500,
                               _time.perf_counter() - t0, req.ByteSize(), 0)
            ctx.abort(grpc.StatusCode.INTERNAL, f"{type(exc).__name__}: {exc}")
        arrays = self.ms.postprocess_arrays(out)  # shared with HTTP v2
        if raw0 is not None:
            # raw in -> raw out (the triton client convention: a client that
            # speaks raw_input_contents reads raw_output_contents)
            outputs, raws = [], []
            for k, v in arrays:
                a, dtname = _resolve_dtype(v)
                outputs.append(pb.ModelInferResponse.InferOutputTensor(
                    name=k, datatype=dtname, shape=list(a.shape)))
                raws.append(np.ascontiguousarray(
                    a.astype(a.dtype.newbyteorder("<"))).tobytes())
            resp = pb.ModelInferResponse(
                model_name=name, model_version="1", id=req.id,
                outputs=outputs, raw_output_contents=raws,
            )
        else:
            resp = pb.ModelInferResponse(
                model_name=name, model_version="1", id=req.id,
                outputs=[_to_tensor(k, v) for k, v in arrays],
            )
        self.ms.logger.log(
            name, "v2-grpc", 200, _time.perf_counter() - t0,
            req.ByteSize(), resp.ByteSize(),
        )
        return resp


def _unary(fn, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def serve_grpc(model_server, port: int = 0, host: str = "127.0.0.1",
               max_workers: int = 4):
    """Attach the gRPC OIP surface to a ModelServer; returns (server, addr)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    svc = InferenceGrpcService(model_server)
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(INFERENCE_SERVICE, {
            "ServerLive": _unary(svc.ServerLive, pb.ServerLiveRequest),
            "ServerReady": _unary(svc.ServerReady, pb.ServerReadyRequest),
            "ModelReady": _unary(svc.ModelReady, pb.ModelReadyRequest),
            "ModelMetadata": _unary(svc.ModelMetadata, pb.ModelMetadataRequest),
            "ModelInfer": _unary(svc.ModelInfer, pb.ModelInferRequest),
        }),
    ))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        # grpc signals bind failure by returning 0, not raising — match the
        # HTTP path's loud OSError so a stolen controller-assigned port can
        # never be advertised as live
        raise OSError(f"gRPC bind to {host}:{port} failed")
    server.start()
    return server, f"{host}:{bound}"


class InferenceGrpcClient:
    """Minimal typed OIP gRPC client (numpy in/out)."""

    def __init__(self, address: str):
        self._chan = grpc.insecure_channel(address)

        def rpc(method, req_cls, resp_cls):
            return self._chan.unary_unary(
                f"/{INFERENCE_SERVICE}/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )

        self._live = rpc("ServerLive", pb.ServerLiveRequest, pb.ServerLiveResponse)
        self._ready = rpc("ServerReady", pb.ServerReadyRequest, pb.ServerReadyResponse)
        self._mready = rpc("ModelReady", pb.ModelReadyRequest, pb.ModelReadyResponse)
        self._meta = rpc("ModelMetadata", pb.ModelMetadataRequest,
                         pb.ModelMetadataResponse)
        self._infer = rpc("ModelInfer", pb.ModelInferRequest, pb.ModelInferResponse)

    def server_live(self) -> bool:
        return self._live(pb.ServerLiveRequest()).live

    def server_ready(self) -> bool:
        return self._ready(pb.ServerReadyRequest()).ready

    def model_ready(self, name: str) -> bool:
        return self._mready(pb.ModelReadyRequest(name=name)).ready

    def model_metadata(self, name: str) -> pb.ModelMetadataResponse:
        return self._meta(pb.ModelMetadataRequest(name=name))

    def infer(self, name: str, arr: np.ndarray, request_id: str = "") -> dict[str, np.ndarray]:
        arr = np.asarray(arr)
        dtype = _NP_TO_DT.get(arr.dtype)
        if dtype is None:
            arr = arr.astype(np.float32)
            dtype = "FP32"
        t = pb.ModelInferRequest.InferInputTensor(
            name="input-0", datatype=dtype, shape=list(arr.shape))
        getattr(t.contents, _DT[dtype][1]).extend(arr.ravel().tolist())
        resp = self._infer(pb.ModelInferRequest(
            model_name=name, id=request_id, inputs=[t]
        ))
        out = {}
        for i, o in enumerate(resp.outputs):
            dt, field = _DT[o.datatype]
            if resp.raw_output_contents:  # raw-speaking server
                out[o.name] = np.frombuffer(
                    resp.raw_output_contents[i],
                    dtype=np.dtype(dt).newbyteorder("<"),
                ).astype(dt).reshape(tuple(o.shape))
            else:
                out[o.name] = np.asarray(
                    getattr(o.contents, field), dtype=dt
                ).reshape(tuple(o.shape))
        return out

    def close(self) -> None:
        self._chan.close()
