"""ServingClient — KServeClient parity.

Reference parity (unverified cites, SURVEY.md §2.5): kserve python/kserve
KServeClient.{create, get, wait_isvc_ready, delete} plus request helpers.
predict()/infer() round-robin over ready replica endpoints (the Service
load-balancer analogue).
"""

from __future__ import annotations

import itertools
import json
import urllib.error
import urllib.request
from dataclasses import dataclass

from kubeflow_tpu.serving.api import InferenceService, validate_isvc
from kubeflow_tpu.serving.controller import ISVC_LABEL
from kubeflow_tpu.utils.retry import (
    BackoffPolicy,
    Deadline,
    hinted_sleep,
    poll_until,
)


@dataclass
class RequestTiming:
    """Per-request accounting predict_timed returns (load-test harness
    input): wall_s (dial to response, INCLUDING 503 re-dial waits),
    ttft_s (engine-reported when streaming, else wall), tokens_per_s
    (engine-reported aggregate decode rate, None for non-streaming
    models), attempts and retry_wait_s (the Retry-After budget path),
    and request_id — the server-assigned/echoed X-Request-Id, the handle
    that joins this timing row to the server's `request` span and log
    lines (docs/slo.md)."""

    wall_s: float
    ttft_s: float
    tokens_per_s: float | None
    attempts: int
    retry_wait_s: float
    request_id: str = ""


class ServingClient:
    def __init__(self, platform):
        self.platform = platform
        self.cluster = platform.cluster
        self._rr = itertools.count()
        # separate stripe counter: sharing _rr would lock the split check and
        # the replica selection to opposite parities (skewing both)
        self._split = itertools.count()

    # ------------------------------------------------------------------ CRUD

    def create(self, isvc: InferenceService) -> InferenceService:
        validate_isvc(isvc)
        return self.cluster.create("inferenceservices", isvc)

    def get(self, name: str, namespace: str = "default") -> InferenceService | None:
        return self.cluster.get("inferenceservices", f"{namespace}/{name}")

    def delete(self, name: str, namespace: str = "default") -> None:
        # ISVC first: deleting pods first would race the controller's
        # self-heal, which could re-spawn server processes for a service
        # that is about to disappear
        self.cluster.delete("inferenceservices", f"{namespace}/{name}")
        for p in self.cluster.list(
            "pods",
            lambda p: p.metadata.labels.get(ISVC_LABEL) == name
            and p.metadata.namespace == namespace,
        ):
            self.cluster.delete("pods", p.key)

    def wait_ready(
        self, name: str, namespace: str = "default", timeout_s: float = 120.0,
        poll_s: float = 0.2,
    ) -> InferenceService:
        def ready() -> InferenceService | None:
            isvc = self.get(name, namespace)
            return isvc if isvc is not None and isvc.status.ready else None

        return poll_until(
            ready,
            timeout_s=timeout_s,
            policy=BackoffPolicy(base_s=0.02, max_s=poll_s, jitter=0.5),
            describe=f"inferenceservice {namespace}/{name} ready",
        )

    # -------------------------------------------------------------- requests

    def _endpoint(self, name: str, namespace: str) -> str:
        isvc = self.get(name, namespace)
        if isvc is None:
            raise KeyError(name)
        # canary traffic split (kserve canaryTrafficPercent): a deterministic
        # 1-in-100 stripe of requests rides the canary endpoints
        pct = isvc.spec.canary_traffic_percent
        if pct > 0 and isvc.spec.canary is not None:
            canary_ready = [e.url for e in isvc.status.canary_endpoints if e.ready]
            if canary_ready and (next(self._split) % 100) < pct:
                return canary_ready[next(self._rr) % len(canary_ready)]
        ready = [e.url for e in isvc.status.endpoints if e.ready]
        if not ready:
            raise RuntimeError(f"inferenceservice {name} has no ready replicas")
        return ready[next(self._rr) % len(ready)]

    # ------------------------------------------------------------- rollouts

    def _read_modify_write(self, name: str, namespace: str, mutate) -> InferenceService:
        return self.cluster.read_modify_write(
            "inferenceservices", f"{namespace}/{name}", mutate
        )

    def set_canary(self, name: str, canary, traffic_percent: int,
                   namespace: str = "default") -> InferenceService:
        """Start (or retune) a canary rollout."""

        def mutate(isvc):
            isvc.spec.canary = canary
            isvc.spec.canary_traffic_percent = traffic_percent
            validate_isvc(isvc)

        return self._read_modify_write(name, namespace, mutate)

    def promote_canary(self, name: str, namespace: str = "default") -> InferenceService:
        """Canary becomes the predictor (100% traffic); canary set removed."""

        def mutate(isvc):
            if isvc.spec.canary is None:
                raise ValueError(f"inferenceservice {name} has no canary")
            isvc.spec.predictor = isvc.spec.canary
            isvc.spec.canary = None
            isvc.spec.canary_traffic_percent = 0

        return self._read_modify_write(name, namespace, mutate)

    def rollback_canary(self, name: str, namespace: str = "default") -> InferenceService:
        """Drop the canary; all traffic back on the stable predictor."""

        def mutate(isvc):
            isvc.spec.canary = None
            isvc.spec.canary_traffic_percent = 0

        return self._read_modify_write(name, namespace, mutate)

    #: re-dials on 503 + Retry-After before giving up (the first attempt
    #: plus max_retries redials)
    RETRY_AFTER_MAX_RETRIES = 2
    #: a server-advertised hint is clamped here — a misconfigured activator
    #: must not park a client for minutes
    RETRY_AFTER_CAP_S = 30.0

    def _post(self, url: str, payload: dict, timeout_s: float,
              stats: dict | None = None) -> dict:
        # timeout_s bounds the WHOLE call — dials, advertised waits, and
        # redials all draw from one budget, so a caller's 2s request can
        # never be parked for minutes by a server hinting Retry-After: 30.
        # `stats` (predict_timed) collects attempts/hinted-wait accounting.
        data = json.dumps(payload).encode()
        deadline = Deadline(timeout_s)
        for attempt in range(self.RETRY_AFTER_MAX_RETRIES + 1):
            if stats is not None:
                stats["attempts"] = attempt + 1
            remaining = deadline.remaining(floor=0.01)
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=remaining) as r:
                    if stats is not None:
                        stats["request_id"] = r.headers.get(
                            "X-Request-Id", "")
                    return json.loads(r.read())
            except urllib.error.HTTPError as exc:
                if stats is not None and exc.headers.get("X-Request-Id"):
                    stats["request_id"] = exc.headers["X-Request-Id"]
                detail = exc.read().decode(errors="replace")
                # 503 + Retry-After (the activator's cold-start/overload
                # signal): the SERVER knows when capacity returns — sleep
                # its advertised interval and re-dial, instead of layering
                # our own backoff schedule on top of its hint
                hint = (exc.headers.get("Retry-After")
                        if exc.code == 503 else None)
                if hint is not None and attempt < self.RETRY_AFTER_MAX_RETRIES:
                    try:
                        delay = float(hint)
                    except ValueError:
                        delay = None  # HTTP-date form: not worth parsing
                    if delay is not None and delay >= 0:
                        # hinted_sleep caps the advertised wait and refuses
                        # to park past the caller's budget — False means
                        # surface the 503 now instead of overshooting
                        if hinted_sleep(delay, cap_s=self.RETRY_AFTER_CAP_S,
                                        deadline=deadline):
                            if stats is not None:
                                stats["retry_wait_s"] = stats.get(
                                    "retry_wait_s", 0.0) + min(
                                    delay, self.RETRY_AFTER_CAP_S)
                            continue
                raise RuntimeError(
                    f"HTTP {exc.code} from {url}: {detail}") from exc
        raise AssertionError("unreachable")  # loop always returns or raises

    def predict(
        self, name: str, instances: list, namespace: str = "default",
        timeout_s: float = 30.0,
    ) -> dict:
        """v1 protocol: {"instances": [...]} -> {"predictions": [...]}."""
        base = self._endpoint(name, namespace)
        return self._post(
            f"{base}/v1/models/{name}:predict", {"instances": instances}, timeout_s
        )

    def predict_timed(
        self, name: str, instances: list, namespace: str = "default",
        timeout_s: float = 30.0,
    ) -> tuple[dict, "RequestTiming"]:
        """Streaming-aware predict: (response, RequestTiming). TTFT and
        tokens/sec come from the SERVER's per-request engine timestamps
        when an engine/fleet serves the model (the response's "timing"
        block — serving/server.py); a model without streaming falls back
        to HTTP wall time. 503 + Retry-After re-dials ride the same
        budgeted `_post` path, and their count/wait land in the timing —
        the load-test harness charges shed-then-retry latency to the
        request, not to nobody."""
        import time as _time

        base = self._endpoint(name, namespace)
        stats: dict = {}
        t0 = _time.perf_counter()
        out = self._post(
            f"{base}/v1/models/{name}:predict", {"instances": instances},
            timeout_s, stats=stats)
        wall = _time.perf_counter() - t0
        timing = out.get("timing") or {}
        ttft = timing.get("ttft_s")
        return out, RequestTiming(
            wall_s=wall,
            ttft_s=wall if ttft is None else ttft,
            tokens_per_s=timing.get("tokens_per_s"),
            attempts=stats.get("attempts", 1),
            retry_wait_s=stats.get("retry_wait_s", 0.0),
            request_id=stats.get("request_id", ""),
        )

    def infer(
        self, name: str, data, shape: list[int], datatype: str = "FP32",
        namespace: str = "default", timeout_s: float = 30.0,
    ) -> dict:
        """v2 Open Inference Protocol infer call."""
        base = self._endpoint(name, namespace)
        payload = {
            "inputs": [
                {"name": "input-0", "shape": shape, "datatype": datatype,
                 "data": data}
            ]
        }
        return self._post(f"{base}/v2/models/{name}/infer", payload, timeout_s)
