"""Serving CR-equivalents: InferenceService.

Reference parity (unverified cites, SURVEY.md §2.5): kserve
pkg/apis/serving/v1beta1 InferenceService{predictor,transformer,explainer}.
Deployment mode is the RawDeployment analogue — the Knative/Istio serverless
stack is intentionally out of scope (SURVEY.md §7 'what NOT to build');
replica processes are managed directly by the ISVC controller.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from kubeflow_tpu.api.common import ObjectMeta


class PredictorRuntime(str, enum.Enum):
    # In-tree JAX runtime: model dir holds config.json + params.msgpack for
    # an in-tree family; server builds the module and jit-compiles predict.
    JAX = "jax"
    # Custom runtime: user supplies "pkg.module:ModelClass" (the kserve
    # custom-predictor container analogue, minus the container).
    CUSTOM = "custom"
    # Framework wrapper runtimes (kserve sklearnserver/torchserve zoo
    # analogue, serving/runtimes.py): artifact pulled by the storage
    # initializer, loaded by the matching wrapper.
    SKLEARN = "sklearn"
    TORCH = "torch"
    XGBOOST = "xgboost"
    LIGHTGBM = "lightgbm"
    PADDLE = "paddle"
    PMML = "pmml"
    # Triton-repository-shaped runtime (config.pbtxt + <version>/model.<ext>
    # layout; triton is the OIP reference server, so it rides the v2 paths).
    TRITON = "triton"


@dataclass
class PredictorSpec:
    runtime: PredictorRuntime = PredictorRuntime.JAX
    # gs:// s3:// pvc:// file:// or bare path; pulled by the storage
    # initializer into the pod's model dir (/mnt/models contract)
    storage_uri: str = ""
    # CUSTOM runtime: import path "package.module:ClassName"
    model_class: str = ""
    replicas: int = 1
    # >0 enables server-side adaptive micro-batching: concurrent requests
    # coalesce into one forward pass of up to this many rows
    max_batch_size: int = 0
    # serve the v2 Open Inference Protocol over gRPC too (kserve serves v2
    # on REST and gRPC); each replica binds an ephemeral gRPC port,
    # surfaced in the pod's grpc-address annotation
    grpc: bool = False
    env: dict[str, str] = field(default_factory=dict)
    # device flag forwarded to the server process (tpu|cpu)
    device: str = ""
    # JAX runtime only: export + serialize the compiled predictor at deploy
    # (serving/aot.py) — replicas load the artifact without retracing, and
    # with a KFT_COMPILE_CACHE env the restart path compiles nothing
    aot: bool = False


@dataclass
class TransformerSpec:
    """Pre/post-processing hop (kserve transformer analogue): a CUSTOM model
    class whose preprocess/postprocess wrap the predictor call."""

    model_class: str = ""
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class ExplainerSpec:
    """:explain hop (kserve explainer analogue): a CUSTOM model class whose
    explain() answers /v1/models/{m}:explain; it receives the predictor
    chain as predict_fn for black-box perturbation."""

    model_class: str = ""
    env: dict[str, str] = field(default_factory=dict)


@dataclass
class AutoscalingSpec:
    """HPA analogue for predictors: the controller samples each replica's
    request counters and sizes the replica set to target_qps_per_replica."""

    min_replicas: int = 1  # 0 enables serverless scale-to-zero
    max_replicas: int = 4
    target_qps_per_replica: float = 10.0
    # seconds between scaling decisions (cooldown)
    scale_interval_s: float = 15.0
    # with min_replicas=0: how long the service must be idle (zero
    # observed qps) before the last replica is reaped (Knative
    # scale-to-zero grace analogue)
    scale_to_zero_grace_s: float = 30.0


@dataclass
class InferenceServiceSpec:
    predictor: PredictorSpec = field(default_factory=PredictorSpec)
    transformer: TransformerSpec | None = None
    explainer: ExplainerSpec | None = None
    # canary rollout (kserve canaryTrafficPercent): a second predictor spec
    # served canary_traffic_percent of requests until promoted/rolled back
    canary: PredictorSpec | None = None
    canary_traffic_percent: int = 0
    autoscaling: AutoscalingSpec | None = None


@dataclass
class ReplicaEndpoint:
    url: str = ""
    ready: bool = False


@dataclass
class InferenceServiceStatus:
    ready: bool = False
    url: str = ""  # primary endpoint (replica 0)
    replicas_ready: int = 0
    endpoints: list[ReplicaEndpoint] = field(default_factory=lambda: [])
    canary_ready: int = 0
    canary_endpoints: list[ReplicaEndpoint] = field(default_factory=lambda: [])
    message: str = ""


@dataclass
class InferenceService:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: InferenceServiceSpec = field(default_factory=InferenceServiceSpec)
    status: InferenceServiceStatus = field(default_factory=InferenceServiceStatus)
    kind: str = "InferenceService"
    api_version: str = "kubeflow-tpu.org/v1beta1"


def validate_isvc(isvc: InferenceService) -> InferenceService:
    if not isvc.metadata.name:
        raise ValueError("inferenceservice: metadata.name required")
    p = isvc.spec.predictor
    if p.replicas < 1:
        raise ValueError("inferenceservice: predictor.replicas must be >= 1")
    if p.runtime != PredictorRuntime.CUSTOM and not p.storage_uri:
        raise ValueError(
            f"inferenceservice: {p.runtime.value} runtime requires storageUri"
        )
    if p.runtime == PredictorRuntime.CUSTOM and not p.model_class:
        raise ValueError(
            "inferenceservice: custom runtime requires modelClass 'module:Class'"
        )
    if isvc.spec.transformer is not None and not isvc.spec.transformer.model_class:
        raise ValueError("inferenceservice: transformer requires modelClass")
    if isvc.spec.explainer is not None and not isvc.spec.explainer.model_class:
        raise ValueError("inferenceservice: explainer requires modelClass")
    if not (0 <= isvc.spec.canary_traffic_percent <= 100):
        raise ValueError(
            "inferenceservice: canaryTrafficPercent must be in [0, 100]"
        )
    if isvc.spec.canary_traffic_percent > 0 and isvc.spec.canary is None:
        raise ValueError(
            "inferenceservice: canaryTrafficPercent requires a canary predictor"
        )
    a = isvc.spec.autoscaling
    if a is not None:
        if not (0 <= a.min_replicas <= a.max_replicas) or a.max_replicas < 1:
            raise ValueError(
                "inferenceservice: autoscaling needs "
                "0 <= minReplicas <= maxReplicas, maxReplicas >= 1 "
                "(minReplicas=0 enables scale-to-zero)"
            )
        if a.target_qps_per_replica <= 0:
            raise ValueError(
                "inferenceservice: autoscaling.targetQpsPerReplica must be > 0"
            )
        if a.scale_to_zero_grace_s <= 0:
            raise ValueError(
                "inferenceservice: autoscaling.scaleToZeroGraceS must be > 0"
            )
    return isvc
