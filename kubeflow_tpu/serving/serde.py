"""InferenceService YAML round-trip (kserve CR manifest parity)."""

from __future__ import annotations

import yaml

from kubeflow_tpu.api.serde import _from_dict, to_dict
from kubeflow_tpu.serving.api import InferenceService


def isvc_to_dict(isvc: InferenceService) -> dict:
    d = to_dict(isvc)
    d.pop("kind", None)
    d.pop("apiVersion", None)
    if not isvc.status.ready and not isvc.status.endpoints:
        d.pop("status", None)
    return {"apiVersion": isvc.api_version, "kind": isvc.kind, **d}


def isvc_to_yaml(isvc: InferenceService) -> str:
    return yaml.safe_dump(isvc_to_dict(isvc), sort_keys=False)


def isvc_from_dict(data: dict) -> InferenceService:
    body = {k: v for k, v in data.items() if k not in ("kind", "apiVersion")}
    return _from_dict(InferenceService, body)


def isvc_from_yaml(text: str) -> InferenceService:
    return isvc_from_dict(yaml.safe_load(text))
