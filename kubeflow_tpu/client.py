"""TrainingClient + Platform — the Python SDK surface (layer L5).

Reference parity: training-operator sdk/python/kubeflow/training
TrainingClient.{create_job, get_job, get_job_logs, wait_for_job_conditions,
delete_job} (unverified, SURVEY.md §2.1). Here the 'cluster' is in-process:
Platform wires the fake-cluster store, gang scheduler, pod runtime, and the
job controller into one unit with real subprocess workloads.
"""

from __future__ import annotations

from pathlib import Path

from kubeflow_tpu.api.common import JobConditionType
from kubeflow_tpu.api.jobs import REPLICA_WORKER, TrainJob, apply_elastic_scale
from kubeflow_tpu.api.validation import validate_job
from kubeflow_tpu.controller.fakecluster import FakeCluster
from kubeflow_tpu.controller.gang import GangScheduler
from kubeflow_tpu.controller.jobcontroller import JobController, delete_job_cascade
from kubeflow_tpu.controller.profile import check_job_admission
from kubeflow_tpu.controller.podruntime import PodRuntime
from kubeflow_tpu.utils.retry import BackoffPolicy, poll_until


class Platform:
    """One in-process 'cluster': apiserver + scheduler + kubelet + operators
    (job controller + experiment controller)."""

    def __init__(
        self,
        log_dir: str = ".kubeflow_tpu/pod-logs",
        capacity_chips: int = 8,
        controller_workers: int = 2,
        liveness=None,
    ):
        """liveness: optional health.LivenessConfig tuning the hang/straggler
        failure detector (docs/health.md); None = defaults."""
        from kubeflow_tpu.controller.devservers import (
            NotebookController,
            PVCViewerController,
        )
        from kubeflow_tpu.controller.autoscaler import TrainingAutoscaler
        from kubeflow_tpu.controller.profile import ProfileController
        from kubeflow_tpu.controller.tensorboard import TensorboardController
        from kubeflow_tpu.pipelines.crd import PipelineRunController
        from kubeflow_tpu.serving.controller import InferenceServiceController
        from kubeflow_tpu.sweep.controller import ExperimentController

        self.cluster = FakeCluster()
        self.cluster.capacity_chips = capacity_chips
        self.pod_runtime = PodRuntime(self.cluster, log_dir=log_dir)
        # ONE chip inventory for both workload classes (docs/scheduler.md):
        # the gang scheduler routes admission through it, registered
        # fleets claim replica chips from it, and /debug/sched +
        # kftpu_sched_* read it
        import os as _os

        from kubeflow_tpu.scheduler.chipsched import (
            DEFAULT_RETRY_AFTER_S,
            ChipScheduler,
        )
        from kubeflow_tpu.utils.envvars import (
            ENV_SCHED_CHIPS_PER_SLICE,
            ENV_SCHED_RETRY_AFTER_S,
        )

        self.chip_scheduler = ChipScheduler(
            capacity_fn=lambda: self.cluster.capacity_chips,
            tracer_fn=lambda: self.cluster.tracer,
            chips_per_slice=int(
                _os.environ.get(ENV_SCHED_CHIPS_PER_SLICE, "8")),
            retry_after_s=float(
                _os.environ.get(ENV_SCHED_RETRY_AFTER_S,
                                str(DEFAULT_RETRY_AFTER_S))))
        self.gang_scheduler = GangScheduler(
            self.cluster, chipsched=self.chip_scheduler)
        self.controller = JobController(
            self.cluster, workers=controller_workers, liveness=liveness,
            # heartbeats live next to the pod logs, so test platforms rooted
            # in a tmp dir keep their liveness state there too
            heartbeat_dir=str(Path(log_dir).parent / "heartbeats"),
        )
        self.experiment_controller = ExperimentController(
            self.cluster, log_reader=self._read_pod_log,
            observation_db=str(Path(log_dir).parent / "sweep-observations.db"),
        )
        self.isvc_controller = InferenceServiceController(
            self.cluster,
            model_cache_dir=str(Path(log_dir).parent / "model-cache"),
            platform=self,
        )
        self.profile_controller = ProfileController(self.cluster)
        self.tensorboard_controller = TensorboardController(self.cluster)
        self.notebook_controller = NotebookController(self.cluster)
        self.pvcviewer_controller = PVCViewerController(self.cluster)
        self.pipelinerun_controller = PipelineRunController(
            self.cluster,
            work_dir=str(Path(log_dir).parent / "pipelines"),
            platform=self,
        )
        self.autoscaler = TrainingAutoscaler(self.cluster, self.gang_scheduler)
        self.metrics_server = None  # started on demand
        self.activator = None  # started on demand (serverless front door)
        self.tracer = None  # enabled on demand (start_tracing)
        #: SLO burn-rate monitor over a bounded TSDB (start_slo):
        #: /debug/slo, the `slo` CLI, and kftpu_slo_* read these
        self.slo_monitor = None
        self.slo_tsdb = None
        self._slo_sampler = None
        #: serving fleets (serving/fleet): "ns/name" -> FleetRouter.
        #: register_fleet() adds one; /metrics aggregates kftpu_fleet_*
        #: over this registry and the activator's queue-depth-aware pick
        #: reads fleet_load_view (callable -> {endpoint url: load})
        self.fleet_routers: dict[str, object] = {}
        self.fleet_load_view = None
        # single registry: observability iterates THIS, so a new controller
        # can never silently fall out of /metrics
        self.controllers = {
            "job": self.controller,
            "experiment": self.experiment_controller,
            "isvc": self.isvc_controller,
            "pipelinerun": self.pipelinerun_controller,
            "profile": self.profile_controller,
            "tensorboard": self.tensorboard_controller,
            "notebook": self.notebook_controller,
            "pvcviewer": self.pvcviewer_controller,
            "autoscaler": self.autoscaler,
        }
        self._started = False

    def start_metrics_server(self, port: int = 0) -> str:
        """Expose GET /metrics (Prometheus text) + /healthz; returns the URL."""
        from kubeflow_tpu.observability import MetricsServer

        if self.metrics_server is None:
            self.metrics_server = MetricsServer(self, port=port).start()
        return self.metrics_server.url

    def start_tracing(self, capacity: int = 4096, trace_dir: str = ""):
        """Arm span tracing + the flight recorder (docs/observability.md).

        Every layer (apiserver, controllers, gang scheduler, pod runtime,
        activator, chaos engine) starts emitting spans into one bounded
        in-memory ring; span counters join /metrics as kftpu_trace_*.
        `trace_dir`, when set, also rides the pod env contract so worker
        processes flush their own spans there for merged export
        (tracing.export_merged_trace). Returns the Tracer."""
        from kubeflow_tpu.tracing import Tracer

        if self.tracer is None:
            self.tracer = Tracer(capacity=capacity, trace_dir=trace_dir,
                                 service="platform")
        self.tracer.armed = True
        self.cluster.tracer = self.tracer  # (re-)arm every layer
        # fleets registered BEFORE tracing was enabled join now —
        # register_fleet/start_tracing must compose in either order
        for router in self.fleet_routers.values():
            self._wire_fleet(router)
        return self.tracer

    def stop_tracing(self) -> None:
        """Freeze span EMISSION everywhere — detach from the cluster AND
        disarm the tracer itself (the apiserver/activator reach it via
        `platform.tracer`, so detaching alone would let HTTP spans keep
        evicting the captured ring). The recorded ring stays on
        `self.tracer`: /debug/trace, /metrics kftpu_trace_*, and snapshot
        exports keep serving exactly what was captured; reading a trace
        never mutates it. start_tracing() re-arms the same recorder."""
        self.cluster.tracer = None
        if self.tracer is not None:
            self.tracer.armed = False

    def register_fleet(self, key: str, router, load_view=None):
        """Attach a serving fleet (serving/fleet.FleetRouter) under
        "namespace/name": its kftpu_fleet_* counters join /metrics, its
        demand signal becomes autoscaler input, and `load_view` (callable
        -> {endpoint url: load}) makes the activator's ready-endpoint
        pick queue-depth-aware (docs/serving.md). When tracing / the SLO
        monitor are live, the router and its engines inherit the
        platform tracer (per-request spans, docs/slo.md) and TSDB
        (decode-tick/TTFT series) unless they brought their own."""
        self.fleet_routers[key] = router
        if load_view is not None:
            self.fleet_load_view = load_view
        self._wire_fleet(router)
        return router

    def _wire_fleet(self, router) -> None:
        # the router owns engine wiring (FleetRouter.wire_monitoring →
        # _wire_engine, the same path add_replica uses), so the platform
        # cannot drift from the fleet's own attach rules
        wire = getattr(router, "wire_monitoring", None)
        if wire is not None:
            wire(tracer=self.tracer, tsdb=self.slo_tsdb)

    def start_slo(self, configs=None, sample_interval_s: float | None = None,
                  capacity: int | None = None):
        """Arm the SLO burn-rate monitor (docs/slo.md): a bounded
        ring-buffer TSDB, a background sampling tick over the existing
        kftpu_* families, and declarative objectives evaluated as
        multi-window burn rates. Registered fleets' engines start
        feeding decode-tick/TTFT series. Surfaces: GET /debug/slo,
        `python -m kubeflow_tpu slo`, kftpu_slo_* in /metrics, and
        FleetRouter.demand_replicas_burn. Returns the SLOMonitor."""
        import os as _os

        from kubeflow_tpu.monitoring import (
            MetricSampler,
            SLOMonitor,
            TimeSeriesStore,
        )
        from kubeflow_tpu.utils.envvars import (
            ENV_SLO_CAPACITY,
            ENV_SLO_TICK_S,
        )

        if self.slo_monitor is not None:
            # a second start_slo re-arms the sampler (the stop_slo
            # freeze contract) — it must not silently DROP overrides
            # the caller believes took effect
            if configs is not None or sample_interval_s is not None \
                    or capacity is not None:
                raise ValueError(
                    "start_slo: the SLO monitor is already running — "
                    "configs/interval/capacity cannot be changed in "
                    "place (series and burn state would be torn); "
                    "build a new Platform to reconfigure")
        else:
            if capacity is None:
                capacity = int(_os.environ.get(ENV_SLO_CAPACITY, "512"))
            if sample_interval_s is None:
                sample_interval_s = float(
                    _os.environ.get(ENV_SLO_TICK_S, "1.0"))
            self.slo_tsdb = TimeSeriesStore(capacity_per_series=capacity)
            self.slo_monitor = SLOMonitor(self.slo_tsdb, configs)
            for router in self.fleet_routers.values():
                self._wire_fleet(router)
            self._slo_sampler = MetricSampler(
                self, self.slo_tsdb, interval_s=sample_interval_s,
                monitor=self.slo_monitor)
        self.slo_tsdb.armed = True
        self._slo_sampler.start()  # re-arms after stop_slo too
        return self.slo_monitor

    def stop_slo(self) -> None:
        """Freeze the monitoring plane: stop the sampling tick AND
        disarm the TSDB, so hot-path producers (the engines' decode-
        tick/TTFT hooks, which keep their reference) degrade to no-ops
        — reading a captured incident window can never evict it (the
        stop_tracing freeze contract applied to samples). The monitor
        and its recorded series stay readable; start_slo() re-arms the
        same store."""
        if self._slo_sampler is not None:
            self._slo_sampler.stop()
        if self.slo_tsdb is not None:
            self.slo_tsdb.armed = False

    def start_activator(self, port: int = 0,
                        host: str = "127.0.0.1") -> str:
        """Serverless front door for InferenceServices (Knative activator
        analogue): stable per-service URLs, canary traffic split, and
        request-holding scale-from-zero. Returns the URL."""
        from kubeflow_tpu.serving.activator import Activator

        if self.activator is None:
            self.activator = Activator(self, port=port, host=host).start()
        return self.activator.url

    def _read_pod_log(self, pod_name: str, namespace: str = "default") -> str:
        path = self.pod_runtime.log_path(pod_name, namespace)
        try:
            return path.read_text()
        except OSError:
            return ""

    def start(self) -> "Platform":
        if not self._started:
            # runtime first, then every registered controller — the registry
            # is the single list (observability iterates the same one)
            self.pod_runtime.start()
            self.gang_scheduler.start()
            for ctrl in self.controllers.values():
                ctrl.start()
            self._started = True
        return self

    def stop(self) -> None:
        self.stop_slo()
        if self.activator is not None:
            self.activator.stop()
            self.activator = None
        for router in self.fleet_routers.values():
            router.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        for ctrl in reversed(list(self.controllers.values())):
            ctrl.stop()
        self.gang_scheduler.stop()
        self.pod_runtime.stop()
        self._started = False

    def __enter__(self) -> "Platform":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class TrainingClient:
    """SDK client; drives jobs through the platform's object store."""

    def __init__(self, platform: Platform):
        self.platform = platform
        self.cluster = platform.cluster

    # ------------------------------------------------------------------ CRUD

    def create_job(self, job: TrainJob) -> TrainJob:
        validate_job(job)
        check_job_admission(self.cluster, job)  # namespace quota (Profile)
        return self.cluster.create("jobs", job)

    def get_job(self, name: str, namespace: str = "default") -> TrainJob | None:
        return self.cluster.get("jobs", f"{namespace}/{name}")

    def list_jobs(self, namespace: str | None = None) -> list[TrainJob]:
        return self.cluster.list(
            "jobs",
            None if namespace is None else (lambda j: j.metadata.namespace == namespace),
        )

    def delete_job(self, name: str, namespace: str = "default") -> None:
        delete_job_cascade(self.cluster, name, namespace)

    def scale_job(
        self, name: str, replicas: int, namespace: str = "default"
    ) -> TrainJob:
        """Elastic scale: set the worker count of a running JAXJob.

        TPU elasticity is slice-granular (SURVEY.md §2.2): the new size must
        keep whole slices, and the change lands as a whole-gang re-mesh
        (coordinator restart + resume from checkpoint), never a live resize.
        Requires an ElasticPolicy and min_replicas <= replicas <= max_replicas.
        """
        return self._read_modify_write(
            name, namespace, lambda job: apply_elastic_scale(job, replicas)
        )

    def _read_modify_write(
        self, name: str, namespace: str, mutate, retries: int = 10
    ) -> TrainJob:
        return self.cluster.read_modify_write(
            "jobs", f"{namespace}/{name}", mutate, retries=retries,
            backoff_s=0.01,
        )

    def suspend_job(self, name: str, namespace: str = "default") -> None:
        def mutate(job: TrainJob) -> None:
            job.spec.run_policy.suspend = True

        self._read_modify_write(name, namespace, mutate)

    def resume_job(self, name: str, namespace: str = "default") -> None:
        def mutate(job: TrainJob) -> None:
            job.spec.run_policy.suspend = False

        self._read_modify_write(name, namespace, mutate)

    # ---------------------------------------------------------------- status

    def train(
        self,
        name: str,
        *,
        family: str = "mnist",
        num_workers: int = 1,
        namespace: str = "default",
        device: str = "auto",
        args: list[str] | None = None,
        elastic: tuple[int, int] | None = None,
        wait: bool = True,
        timeout_s: float = 3600.0,
    ) -> dict[str, float]:
        """High-level train() convenience (the reference SDK's
        TrainingClient.train HF-fine-tune helper, SURVEY.md §2.1 — here over
        the in-tree model families instead of HF images): build a JAXJob
        around `python -m examples.<family>`, submit it, wait, and return
        the final metrics parsed from worker-0's log.

        family: mnist | resnet | bert | bert_pretrain | gpt
        args:   extra example flags (e.g. ["--steps=200", "--bf16"])
        elastic: (min_replicas, max_replicas) to attach an ElasticPolicy
        """
        import sys as _sys

        from kubeflow_tpu.api.jobs import build_example_train_job

        job = build_example_train_job(
            name, family=family, num_workers=num_workers, namespace=namespace,
            device=device, args=args, elastic=elastic,
            # in-process: same environment, so the concrete interpreter and
            # the repo root are correct here
            interpreter=_sys.executable,
            working_dir=str(Path(__file__).resolve().parents[1]),
        )
        self.create_job(job)
        if not wait:
            return {}
        done = self.wait_for_job_conditions(
            name, namespace, timeout_s=timeout_s
        )
        if not done.status.is_succeeded:
            failed = next(
                (c for c in done.status.conditions
                 if c.type == JobConditionType.FAILED), None
            )
            detail = f": {failed.message}" if failed and failed.message else ""
            raise RuntimeError(f"train job {name} failed{detail}")
        from kubeflow_tpu.train.metrics import extract_final_metrics

        return extract_final_metrics(self.get_job_logs(name, namespace))

    def wait_for_job_conditions(
        self,
        name: str,
        namespace: str = "default",
        expected: tuple[JobConditionType, ...] = (
            JobConditionType.SUCCEEDED,
            JobConditionType.FAILED,
        ),
        timeout_s: float = 120.0,
        poll_s: float = 0.1,
    ) -> TrainJob:
        def reached() -> TrainJob | None:
            job = self.get_job(name, namespace)
            if job is not None:
                for cond in expected:
                    if job.status.has_condition(cond):
                        return job
            return None

        try:
            return poll_until(
                reached,
                timeout_s=timeout_s,
                policy=BackoffPolicy(base_s=0.02, max_s=poll_s, jitter=0.5),
            )
        except TimeoutError:
            raise TimeoutError(
                f"job {namespace}/{name} did not reach {expected} "
                f"in {timeout_s}s"
            ) from None

    def get_job_logs(
        self, name: str, namespace: str = "default", rtype: str = "worker", index: int = 0
    ) -> str:
        path = self.platform.pod_runtime.log_path(f"{name}-{rtype}-{index}", namespace)
        return Path(path).read_text() if Path(path).exists() else ""

    def get_events(self, name: str, namespace: str = "default") -> list:
        return self.cluster.events_for(f"{namespace}/{name}")
