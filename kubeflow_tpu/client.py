"""TrainingClient + Platform — the Python SDK surface (layer L5).

Reference parity: training-operator sdk/python/kubeflow/training
TrainingClient.{create_job, get_job, get_job_logs, wait_for_job_conditions,
delete_job} (unverified, SURVEY.md §2.1). Here the 'cluster' is in-process:
Platform wires the fake-cluster store, gang scheduler, pod runtime, and the
job controller into one unit with real subprocess workloads.
"""

from __future__ import annotations

import time
from pathlib import Path

from kubeflow_tpu.api.common import JobConditionType
from kubeflow_tpu.api.jobs import TrainJob
from kubeflow_tpu.api.validation import validate_job
from kubeflow_tpu.controller.fakecluster import FakeCluster
from kubeflow_tpu.controller.gang import GangScheduler
from kubeflow_tpu.controller.jobcontroller import JobController
from kubeflow_tpu.controller.podruntime import PodRuntime


class Platform:
    """One in-process 'cluster': apiserver + scheduler + kubelet + operator."""

    def __init__(
        self,
        log_dir: str = ".kubeflow_tpu/pod-logs",
        capacity_chips: int = 8,
        controller_workers: int = 2,
    ):
        self.cluster = FakeCluster()
        self.cluster.capacity_chips = capacity_chips
        self.pod_runtime = PodRuntime(self.cluster, log_dir=log_dir)
        self.gang_scheduler = GangScheduler(self.cluster)
        self.controller = JobController(self.cluster, workers=controller_workers)
        self._started = False

    def start(self) -> "Platform":
        if not self._started:
            self.pod_runtime.start()
            self.gang_scheduler.start()
            self.controller.start()
            self._started = True
        return self

    def stop(self) -> None:
        self.controller.stop()
        self.gang_scheduler.stop()
        self.pod_runtime.stop()
        self._started = False

    def __enter__(self) -> "Platform":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class TrainingClient:
    """SDK client; drives jobs through the platform's object store."""

    def __init__(self, platform: Platform):
        self.platform = platform
        self.cluster = platform.cluster

    # ------------------------------------------------------------------ CRUD

    def create_job(self, job: TrainJob) -> TrainJob:
        validate_job(job)
        return self.cluster.create("jobs", job)

    def get_job(self, name: str, namespace: str = "default") -> TrainJob | None:
        return self.cluster.get("jobs", f"{namespace}/{name}")

    def list_jobs(self, namespace: str | None = None) -> list[TrainJob]:
        return self.cluster.list(
            "jobs",
            None if namespace is None else (lambda j: j.metadata.namespace == namespace),
        )

    def delete_job(self, name: str, namespace: str = "default") -> None:
        key = f"{namespace}/{name}"
        for p in self.cluster.list(
            "pods", lambda p: p.metadata.labels.get("kubeflow-tpu.org/job-name") == name
            and p.metadata.namespace == namespace
        ):
            self.cluster.delete("pods", p.key)
        self.cluster.delete("podgroups", key)
        self.cluster.delete("jobs", key)

    def suspend_job(self, name: str, namespace: str = "default") -> None:
        job = self.get_job(name, namespace)
        if job is None:
            raise KeyError(name)
        job.spec.run_policy.suspend = True
        self.cluster.update("jobs", job)

    def resume_job(self, name: str, namespace: str = "default") -> None:
        job = self.get_job(name, namespace)
        if job is None:
            raise KeyError(name)
        job.spec.run_policy.suspend = False
        self.cluster.update("jobs", job)

    # ---------------------------------------------------------------- status

    def wait_for_job_conditions(
        self,
        name: str,
        namespace: str = "default",
        expected: tuple[JobConditionType, ...] = (
            JobConditionType.SUCCEEDED,
            JobConditionType.FAILED,
        ),
        timeout_s: float = 120.0,
        poll_s: float = 0.1,
    ) -> TrainJob:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            job = self.get_job(name, namespace)
            if job is not None:
                for cond in expected:
                    if job.status.has_condition(cond):
                        return job
            time.sleep(poll_s)
        raise TimeoutError(
            f"job {namespace}/{name} did not reach {expected} in {timeout_s}s"
        )

    def get_job_logs(
        self, name: str, namespace: str = "default", rtype: str = "worker", index: int = 0
    ) -> str:
        path = self.platform.pod_runtime.log_path(f"{name}-{rtype}-{index}")
        return Path(path).read_text() if Path(path).exists() else ""

    def get_events(self, name: str, namespace: str = "default") -> list:
        return self.cluster.events_for(f"{namespace}/{name}")
