"""ChipScheduler — one chip inventory for BOTH workload classes.

ROADMAP item 3's arbiter: until now `GangScheduler` (controller/gang.py)
first-fit a private ledger for training gangs while the serving tier
allocated engines with no chip accounting at all — two tenants of the
same repo, each blind to the other's usage, and the autoscaler's paired
free/demand reads raced both. This module is the single source of truth
they all route through:

  - **slice-aware bin-packing**: the inventory is slices × chips
    (``chips_per_slice``). Gangs place whole-slice (topology-sized,
    slice-multiple gangs) or contiguous-within-a-slice, with a spanning
    fallback so admission remains a pure total-capacity predicate (a
    gang that fits by count always binds — fragmentation changes the
    *placement*, never the *admission*, preserving the pre-ledger
    contract every gang test pins). Serving replicas best-fit into the
    fullest slice that holds them, keeping whole slices free for gangs.
  - **priority classes**: serving > interactive > batch
    (``PRIORITY_SERVING/INTERACTIVE/BATCH``, aligned with the gang
    scheduler's PriorityClass ladder — "system-critical" == serving).
  - **preemption**: a claim that cannot fit may evict strictly-lower-
    priority *gang* claims (lowest priority first, youngest first —
    least sunk work). Feasibility is decided on a scratch copy BEFORE
    any eviction commits, so an infeasible preemption never thrashes a
    batch job through a pointless restart. Each committed eviction
    emits a ``sched.preempt`` span whose context is handed to the
    registered ``evictor`` — the gang scheduler stamps it on the victim
    pods (CARRIER_ANNOTATION + the retryable PREEMPTED exit class), so
    the job's ``job.gang_restart`` parent-links to the preemption and
    restart-overhead attribution + the compile-cache warm resume
    compose unchanged (docs/scheduler.md).
  - **fair-share tenant quotas**: ``set_shares({tenant: weight})`` arms
    weighted max-min entitlements (dominant-resource fairness over the
    single chip resource). A tenant over its entitlement may *borrow*
    idle chips — but a borrower can never preempt anyone (the quota
    analogue of gang.py's "quota-blocked gangs never use preempted
    chips"), and its borrowed claims become reclaim-eligible: an
    under-entitlement claimant may evict borrowed gang claims at equal
    priority, counted separately as quota reclaims.
  - **denial contract**: every refused claim is a ``Deny`` carrying the
    reason (frozen / quota / capacity) and a ``retry_after_s`` hint —
    the activator's Retry-After idiom, scheduler edition — plus a
    traced ``sched.deny`` event so a starved fleet's burn alert has a
    cause to point at.
  - **chaos**: ``freeze()`` (KFTPU_PROF_CHAOS="sched_freeze:1" via the
    diurnal-storm drill) stops all granting; the serving burn signal
    keeps demanding, the SLO alert fires, and the prof gate fails —
    tests/test_prof_gate.py pins both sides.

Thread-safety: one ``make_lock``-named mutex guards the ledger
(GuardedState-checked under KFTPU_LOCKCHECK=1). Evictor callbacks are
invoked AFTER the lock is released — the gang scheduler re-enters its
own ``_mu`` there, and the only cross-module order is the acyclic
gang._mu -> chipsched._mu (admission) with no reverse edge.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from kubeflow_tpu.analysis.lockcheck import GuardedState, make_lock
from kubeflow_tpu.analysis.protocheck.eventlog import log_event

#: The platform priority ladder (ISSUE: serving > interactive > batch).
#: Values align with gang.PRIORITY_CLASSES so a gang claim's PodGroup
#: priority drops in unchanged: "system-critical" gangs rank with
#: serving, "high" with interactive, default batch at 0.
PRIORITY_SERVING = 2000
PRIORITY_INTERACTIVE = 1000
PRIORITY_BATCH = 0

#: Default Retry-After hint on a deny (seconds) — the caller's backoff
#: floor when nothing better (a cold-start EWMA) is known.
DEFAULT_RETRY_AFTER_S = 0.5


@dataclass(frozen=True)
class Grant:
    """A successful claim. ``slices`` is the placement ((slice index,
    chips) pairs); ``placement`` names the strategy that produced it
    (whole_slice / contiguous / spanning / none for 0-chip claims)."""

    key: str
    chips: int
    slices: tuple = ()
    placement: str = "none"
    borrowed: int = 0
    preempted: tuple = ()
    ok = True


@dataclass(frozen=True)
class Deny:
    """A refused claim: reason in {frozen, quota, capacity}, plus the
    Retry-After hint and the free count at decision time."""

    key: str
    chips: int
    reason: str
    retry_after_s: float = DEFAULT_RETRY_AFTER_S
    free: int = 0
    ok = False


@dataclass
class _Claim:
    key: str
    uid: str
    kind: str  # "gang" | "replica"
    tenant: str
    chips: int
    priority: int
    seq: int
    slices: tuple = ()
    borrowed: int = 0
    preemptible: bool = True


def _counter_dict() -> dict:
    return {
        "grants_total": 0,
        "denies_total": 0,
        "preemptions_total": 0,
        "quota_borrows_total": 0,
        "quota_reclaims_total": 0,
        "resumes_total": 0,
        "reclaimed_chips_total": 0,
        "double_count_avoided_chips_total": 0,
    }


class ChipScheduler:
    """The shared ledger (module docstring). Construct once per cluster
    (client.Platform wires one through GangScheduler, the training
    autoscaler, and every FleetScaler); standalone construction with a
    fixed ``capacity`` serves the unit drills."""

    def __init__(self, capacity: int = 0, chips_per_slice: int = 8,
                 capacity_fn=None, tracer_fn=None,
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S):
        """capacity_fn() -> live chip capacity (the cluster's
        capacity_chips, which tests resize after construction); a fixed
        ``capacity`` otherwise. tracer_fn() -> tracer-or-None, read per
        event (races stop_tracing, same single-read idiom as gang.py)."""
        if chips_per_slice < 1:
            raise ValueError("chips_per_slice must be >= 1")
        self._capacity = capacity
        self._capacity_fn = capacity_fn
        self.chips_per_slice = chips_per_slice
        self.retry_after_s = retry_after_s
        self._tracer_fn = tracer_fn or (lambda: None)
        #: evictor(key, uid, chips, carrier, by) — registered by the
        #: gang scheduler; turns a committed preemption into the victim
        #: pods' FAILED(preempted) writes. Called WITHOUT _mu held.
        self.evictor = None
        self.metrics = _counter_dict()
        #: preempt -> resume latency samples, seconds (histogram source)
        self.preempt_to_resume_s: list[float] = []
        #: tenant -> share weight; empty == quotas unenforced
        self.shares: dict[str, float] = {}
        self._mu = make_lock("scheduler.ChipScheduler._mu")
        # claims IS the inventory; preempted_at carries the resume-latency
        # clock across a victim's restart (key survives the podgroup's
        # delete/recreate cycle — same ns/name, new uid).
        self._guarded = GuardedState(
            self._mu, claims={}, preempted_at={}, frozen=False, seq=0)

    # ------------------------------------------------------------ config

    @property
    def capacity_chips(self) -> int:
        return int(self._capacity_fn() if self._capacity_fn else self._capacity)

    def set_shares(self, shares: dict[str, float]) -> None:
        """Arm fair-share quotas. Weighted max-min: tenant i is entitled
        to capacity * w_i / sum(w). Tenants absent from the map are
        entitled to 0 — they run entirely on borrowed (reclaimable)
        chips."""
        if any(w <= 0 for w in shares.values()):
            raise ValueError("share weights must be positive")
        with self._mu:
            self.shares = dict(shares)

    def freeze(self) -> None:
        """Chaos: stop granting (sched_freeze). Held claims keep their
        chips; releases still work — the outage is admission-only."""
        with self._mu:
            self._guarded.frozen = True

    def thaw(self) -> None:
        with self._mu:
            self._guarded.frozen = False

    # ------------------------------------------------------------ claims

    def claim_gang(self, key: str, uid: str, chips: int, priority: int =
                   PRIORITY_BATCH, tenant: str = "default",
                   preempt: bool = False) -> Grant | Deny:
        """Place a whole gang (whole-slice-or-contiguous, spanning
        fallback). A same-key claim while one is held is denied —
        callers release (or grow_gang) first."""
        res, evictions = self._claim("gang", key, uid, chips, priority,
                                     tenant, preempt)
        self._run_evictions(evictions)
        return res

    def claim_replica(self, key: str, chips: int = 1, priority: int =
                      PRIORITY_SERVING, tenant: str = "serving",
                      preempt: bool = True) -> Grant | Deny:
        """Place one serving replica's chips (best-fit into the fullest
        slice that holds them). Preemption-then-grant is the default
        escalation: a serving scale-up that cannot fit evicts the
        lowest-priority/youngest batch gang (module docstring)."""
        res, evictions = self._claim("replica", key, "", chips, priority,
                                     tenant, preempt)
        self._run_evictions(evictions)
        return res

    def grow_gang(self, key: str, uid: str, extra: int) -> bool:
        """Add chips to a held gang claim (the late-member path). Pure
        capacity growth — no preemption, no quota borrow upgrade."""
        if extra <= 0:
            return True
        with self._mu:
            if self._guarded.frozen:
                return False
            c = self._guarded.claims.get(key)
            if c is None or c.uid != uid:
                return False
            placed = self._place_gang(self._slice_free(), extra)
            if placed is None:
                return False
            merged: dict[int, int] = dict(c.slices)
            for idx, n in placed[0]:
                merged[idx] = merged.get(idx, 0) + n
            c.slices = tuple(sorted(merged.items()))
            c.chips += extra
            self.metrics["grants_total"] += 1
            log_event("ledger", "sched", "grow", key=key, chips=c.chips,
                      extra=extra, capacity=self.capacity_chips,
                      free=self._free_locked())
            return True

    def release(self, key: str, uid: str = "") -> int:
        """Return a claim's chips to the pool. ``uid`` guards gang
        releases across delete/recreate races (gang.py's ledger
        contract); empty matches any. Returns chips freed (0 if the
        claim was absent or uid-mismatched)."""
        with self._mu:
            c = self._guarded.claims.get(key)
            if c is None or (uid and c.uid and c.uid != uid):
                return 0
            self._guarded.claims.pop(key)
            self.metrics["reclaimed_chips_total"] += c.chips
            log_event("ledger", "sched", "release", key=key,
                      chips=c.chips, capacity=self.capacity_chips,
                      free=self._free_locked())
            return c.chips

    def audit(self) -> dict:
        """Chip-conservation audit — the drill suites call this after a
        storm. Asserts, under the ledger lock: every claim's slice
        placement sums to exactly its chips, no slice is oversubscribed,
        and the per-slice free chips account for every held chip (so a
        lost or double-counted grant cannot hide). Returns the audited
        figures for the caller's own asserts."""
        with self._mu:
            cap = self.capacity_chips
            claims = self._guarded.claims
            for c in claims.values():
                placed = sum(k for _, k in c.slices)
                assert placed == c.chips, (
                    f"ledger audit: claim {c.key!r} holds {c.chips} "
                    f"chips but its slices sum to {placed}")
            slice_free = self._slice_free()
            assert min(slice_free, default=0) >= 0, (
                f"ledger audit: slice oversubscribed: {slice_free}")
            held = sum(c.chips for c in claims.values())
            assert sum(slice_free) == cap - held, (
                f"ledger audit: chips not conserved: per-slice free "
                f"{slice_free} != capacity {cap} - held {held}")
            return {"capacity": cap, "held": held,
                    "free": cap - held, "claims": len(claims),
                    "slice_free": slice_free}

    # ------------------------------------------------------------- views

    def free_chips(self) -> int:
        with self._mu:
            return self._free_locked()

    def used_chips(self) -> int:
        with self._mu:
            return sum(c.chips for c in self._guarded.claims.values())

    def held(self, key: str) -> bool:
        with self._mu:
            return key in self._guarded.claims

    def tenant_usage(self) -> dict[str, int]:
        with self._mu:
            out: dict[str, int] = {}
            for c in self._guarded.claims.values():
                out[c.tenant] = out.get(c.tenant, 0) + c.chips
            return out

    def entitlements(self) -> dict[str, int]:
        """tenant -> entitled chips under the armed shares (empty when
        quotas are unenforced)."""
        with self._mu:
            return self._entitlements_locked()

    def note_double_count_avoided(self, chips: int) -> None:
        """The race-fix witness: chips a pending gang ALREADY holds in
        the ledger, which the old paired free/demand reads would have
        counted twice (once as demand, once as used). The combined
        snapshot skips them — and counts what it skipped."""
        if chips > 0:
            with self._mu:
                self.metrics["double_count_avoided_chips_total"] += chips

    def snapshot(self) -> dict:
        """One consistent view (report.py / /metrics / /debug/sched)."""
        with self._mu:
            cap = self.capacity_chips
            free = self._slice_free()
            claims = [
                {
                    "key": c.key, "kind": c.kind, "tenant": c.tenant,
                    "chips": c.chips, "priority": c.priority,
                    # JSON-native pairs: /debug/sched consumers must
                    # compare equal to a direct build (surface agreement)
                    "slices": [list(s) for s in c.slices],
                    "borrowed": c.borrowed,
                    "seq": c.seq,
                }
                for c in sorted(self._guarded.claims.values(),
                                key=lambda c: c.seq)
            ]
            usage: dict[str, int] = {}
            borrowed: dict[str, int] = {}
            for c in self._guarded.claims.values():
                usage[c.tenant] = usage.get(c.tenant, 0) + c.chips
                if c.borrowed:
                    borrowed[c.tenant] = borrowed.get(c.tenant, 0) + c.borrowed
            ents = self._entitlements_locked()
            tenants = {
                t: {
                    "share": self.shares.get(t, 0.0),
                    "entitled_chips": ents.get(t, 0),
                    "used_chips": usage.get(t, 0),
                    "borrowed_chips": borrowed.get(t, 0),
                }
                for t in sorted(set(self.shares) | set(usage))
            }
            return {
                "capacity_chips": cap,
                "chips_per_slice": self.chips_per_slice,
                "used_chips": sum(c.chips
                                  for c in self._guarded.claims.values()),
                "free_chips": max(0, sum(free)),
                "slice_free": list(free),
                "frozen": self._guarded.frozen,
                "quota_enforced": bool(self.shares),
                "claims": claims,
                "tenants": tenants,
                "metrics": dict(self.metrics),
                "preempt_to_resume_s": list(self.preempt_to_resume_s),
            }

    # ---------------------------------------------------------- internals

    def _free_locked(self) -> int:
        return self.capacity_chips - sum(
            c.chips for c in self._guarded.claims.values())

    def _slice_free(self, claims=None) -> list[int]:
        """Free chips per slice. The last slice may be partial when
        capacity is not a slice multiple."""
        cap = self.capacity_chips
        cps = self.chips_per_slice
        n = max(1, -(-cap // cps)) if cap > 0 else 1
        free = [max(0, min(cps, cap - i * cps)) for i in range(n)]
        source = self._guarded.claims if claims is None else claims
        for c in source.values():
            for idx, k in c.slices:
                if idx < len(free):
                    free[idx] -= k
        return free

    def _place_gang(self, free: list[int], chips: int):
        """((slice, chips) pairs, strategy) or None. Whole slices for
        slice-multiple gangs, else contiguous within one slice (best
        fit), else span slices in order — admission stays a total-free
        predicate (module docstring)."""
        cps = self.chips_per_slice
        if chips >= cps and chips % cps == 0:
            whole = [i for i, f in enumerate(free) if f == cps]
            need = chips // cps
            if len(whole) >= need:
                return tuple((i, cps) for i in whole[:need]), "whole_slice"
        if chips <= cps:
            fits = [i for i, f in enumerate(free) if f >= chips]
            if fits:
                best = min(fits, key=lambda i: free[i])
                return ((best, chips),), "contiguous"
        if sum(f for f in free if f > 0) >= chips:
            placed, left = [], chips
            for i, f in enumerate(free):
                if left <= 0:
                    break
                take = min(max(0, f), left)
                if take:
                    placed.append((i, take))
                    left -= take
            if left <= 0:
                return tuple(placed), "spanning"
        return None

    def _place_replica(self, free: list[int], chips: int):
        """Best-fit: the FULLEST slice that still holds the replica —
        dense packing keeps whole slices free for gangs."""
        fits = [i for i, f in enumerate(free) if f >= chips]
        if fits:
            best = min(fits, key=lambda i: free[i])
            return ((best, chips),), "contiguous"
        # a replica wider than any single slice's free chips spans
        return self._place_gang(free, chips)

    def _entitlements_locked(self) -> dict[str, int]:
        if not self.shares:
            return {}
        total = sum(self.shares.values())
        cap = self.capacity_chips
        return {t: int(cap * w / total) for t, w in self.shares.items()}

    def _tenant_used_locked(self, tenant: str, claims) -> int:
        return sum(c.chips for c in claims.values() if c.tenant == tenant)

    def _claim(self, kind, key, uid, chips, priority, tenant, preempt):
        """The one admission path. Returns (Grant|Deny, evictions) where
        evictions are executed by the caller AFTER _mu is released."""
        tracer = self._tracer_fn()
        with self._mu:
            if self._guarded.frozen:
                return self._deny(tracer, key, chips, tenant, "frozen"), ()
            claims = self._guarded.claims
            if key in claims:
                # double-claim: the ledger is the single source — a
                # caller that lost track must release first
                return self._deny(tracer, key, chips, tenant,
                                  "capacity"), ()
            # quota: entitlement under the armed shares; over-entitlement
            # chips are a borrow, and borrowers never preempt
            borrowed = 0
            ents = self._entitlements_locked()
            if ents:
                ent = ents.get(tenant, 0)
                used_t = self._tenant_used_locked(tenant, claims)
                borrowed = max(0, min(chips, used_t + chips - ent))
            place = (self._place_gang if kind == "gang"
                     else self._place_replica)
            placed = place(self._slice_free(), chips) if chips > 0 else ((), "none")
            evict_plan: list[_Claim] = []
            reclaims = 0
            if placed is None and preempt and borrowed == 0:
                # feasibility on a SCRATCH copy first: an infeasible
                # preemption must not thrash victims through restarts
                scratch = dict(claims)
                for v in self._victims_locked(priority, scratch):
                    scratch.pop(v.key)
                    evict_plan.append(v)
                    if v.borrowed:
                        reclaims += 1
                    placed = place(self._slice_free(scratch), chips)
                    if placed is not None:
                        break
                if placed is None:
                    evict_plan, reclaims = [], 0
            if placed is None:
                # a borrower's only escalation would be preemption, and
                # borrowers never preempt: that refusal is a QUOTA deny
                reason = "quota" if borrowed else "capacity"
                return self._deny(tracer, key, chips, tenant, reason), ()
            evictions = []
            for v in evict_plan:
                claims.pop(v.key, None)
                self._guarded.preempted_at[v.key] = time.monotonic()
                self.metrics["preemptions_total"] += 1
                self.metrics["reclaimed_chips_total"] += v.chips
                carrier = ""
                if tracer is not None:
                    sp = tracer.event(
                        "sched.preempt", parent=None, victim=v.key,
                        chips=v.chips, by=key, tenant=v.tenant,
                        victim_priority=v.priority, priority=priority,
                        reclaim=bool(v.borrowed))
                    ctx = sp.context
                    carrier = ctx.to_header() if ctx is not None else ""
                evictions.append((v.key, v.uid, v.chips, carrier, key))
            self.metrics["quota_reclaims_total"] += reclaims
            self._guarded.seq += 1
            claims[key] = _Claim(
                key=key, uid=uid, kind=kind, tenant=tenant, chips=chips,
                priority=priority, seq=self._guarded.seq,
                slices=placed[0], borrowed=borrowed)
            self.metrics["grants_total"] += 1
            if borrowed:
                self.metrics["quota_borrows_total"] += 1
            t0 = self._guarded.preempted_at.pop(key, None)
            if t0 is not None and kind == "gang":
                self.preempt_to_resume_s.append(time.monotonic() - t0)
                self.metrics["resumes_total"] += 1
            log_event("ledger", "sched", "grant", key=key,
                      chips=chips, borrowed=borrowed,
                      capacity=self.capacity_chips,
                      free=self._free_locked(),
                      evicted=[v.key for v in evict_plan])
            return Grant(key=key, chips=chips, slices=placed[0],
                         placement=placed[1], borrowed=borrowed,
                         preempted=tuple(v.key for v in evict_plan)), \
                tuple(evictions)

    def _victims_locked(self, priority: int, claims: dict):
        """Preemption candidates in eviction order: gang claims strictly
        below the claimant's priority, plus borrowed gang claims at-or-
        below it (quota reclaim). Lowest priority first, youngest first
        within a level — least sunk work lost (gang.py's rule)."""
        out = [
            c for c in claims.values()
            if c.kind == "gang" and c.preemptible
            and (c.priority < priority
                 or (c.borrowed > 0 and c.priority <= priority))
        ]
        out.sort(key=lambda c: c.seq, reverse=True)
        out.sort(key=lambda c: c.priority)
        return out

    def _deny(self, tracer, key, chips, tenant, reason) -> Deny:
        self.metrics["denies_total"] += 1
        free = self._free_locked()
        if tracer is not None:
            tracer.event("sched.deny", parent=None, key=key, chips=chips,
                         tenant=tenant, reason=reason, free=free,
                         retry_after_s=self.retry_after_s)
        return Deny(key=key, chips=chips, reason=reason,
                    retry_after_s=self.retry_after_s, free=max(0, free))

    def _run_evictions(self, evictions) -> None:
        # outside _mu: the evictor re-enters the gang scheduler's lock
        for key, uid, chips, carrier, by in evictions:
            if self.evictor is not None:
                self.evictor(key, uid, chips, carrier, by=by)
