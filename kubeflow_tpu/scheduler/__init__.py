"""kubeflow_tpu.scheduler — the cluster-wide chip scheduler.

One chip inventory for both workload classes: training gangs (via
controller/gang.py) and serving fleets (via serving/fleet/scaler.py)
claim and release through the same slice-aware, priority/preemption,
fair-share ledger (docs/scheduler.md)."""

from kubeflow_tpu.scheduler.chipsched import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    PRIORITY_SERVING,
    ChipScheduler,
    Deny,
    Grant,
)
from kubeflow_tpu.scheduler.report import (
    build_sched_report,
    build_sched_report_from_scheduler,
    render_sched_text,
)

__all__ = [
    "PRIORITY_BATCH",
    "PRIORITY_INTERACTIVE",
    "PRIORITY_SERVING",
    "ChipScheduler",
    "Deny",
    "Grant",
    "build_sched_report",
    "build_sched_report_from_scheduler",
    "render_sched_text",
]
