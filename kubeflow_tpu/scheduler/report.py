"""Chip-scheduler report — the ONE build path every sched surface serves.

`build_sched_report` assembles the canonical report dict from the
ChipScheduler's consistent snapshot: inventory (capacity / free / per-
slice occupancy), the claim table, per-tenant share accounting, and the
grant/deny/preempt counters with preempt-to-resume latency stats.
`GET /debug/sched`, the ``sched`` CLI subcommand, and tests all read
THIS module, so the surfaces can never disagree about who holds which
chips (tests/test_chipsched.py pins exact agreement, the
TestSurfacesAgree pattern from /debug/slo).
"""

from __future__ import annotations


def build_sched_report_from_scheduler(sched) -> dict:
    """The canonical report for one ChipScheduler."""
    snap = sched.snapshot()
    samples = snap.pop("preempt_to_resume_s")
    stats = {"count": len(samples)}
    if samples:
        ordered = sorted(samples)
        stats["mean_s"] = sum(ordered) / len(ordered)
        stats["max_s"] = ordered[-1]
    snap["preempt_to_resume"] = stats
    return snap


def build_sched_report(platform) -> dict:
    """Live-platform form: the platform's shared chip scheduler."""
    sched = getattr(platform, "chip_scheduler", None)
    if sched is None:
        raise ValueError("platform has no chip scheduler")
    return build_sched_report_from_scheduler(sched)


def render_sched_text(report: dict) -> str:
    """Operator-facing table form (the default ``sched`` CLI rendering)."""
    lines = ["kftpu sched"]
    lines.append(
        f"inventory: {report['used_chips']}/{report['capacity_chips']} "
        f"chips used ({report['free_chips']} free, "
        f"{report['chips_per_slice']} chips/slice)"
        + ("  FROZEN" if report.get("frozen") else ""))
    lines.append(
        "slices: "
        + " ".join(f"[{i}:{f}free]"
                   for i, f in enumerate(report.get("slice_free", []))))
    claims = report.get("claims", [])
    if claims:
        lines.append("claims:")
        lines.append(
            "  key                           kind     tenant     chips"
            "  prio   borrowed  slices")
        for c in claims:
            slices = ",".join(f"{i}x{n}" for i, n in c["slices"])
            lines.append(
                f"  {c['key']:<28}  {c['kind']:<7}  {c['tenant']:<9}  "
                f"{c['chips']:>5}  {c['priority']:>5}  "
                f"{c['borrowed']:>8}  {slices}")
    else:
        lines.append("claims: none")
    tenants = report.get("tenants", {})
    if tenants:
        hdr = "enforced" if report.get("quota_enforced") else "unenforced"
        lines.append(f"tenants ({hdr}):")
        for t, info in sorted(tenants.items()):
            lines.append(
                f"  {t:<12} share={info['share']:<4g} "
                f"entitled={info['entitled_chips']} "
                f"used={info['used_chips']} "
                f"borrowed={info['borrowed_chips']}")
    m = report.get("metrics", {})
    lines.append(
        f"counters: grants={m.get('grants_total', 0)} "
        f"denies={m.get('denies_total', 0)} "
        f"preemptions={m.get('preemptions_total', 0)} "
        f"resumes={m.get('resumes_total', 0)} "
        f"borrows={m.get('quota_borrows_total', 0)} "
        f"reclaims={m.get('quota_reclaims_total', 0)}")
    pr = report.get("preempt_to_resume", {})
    if pr.get("count"):
        lines.append(
            f"preempt->resume: {pr['count']} sample(s), "
            f"mean {pr['mean_s']:.3f}s, max {pr['max_s']:.3f}s")
    return "\n".join(lines) + "\n"
