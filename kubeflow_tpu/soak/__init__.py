"""kftpu-storm — the production-day soak (ROADMAP item 6).

One seeded, tick-driven "day in production" composing every subsystem
the platform has grown: diurnal traffic waves against a FleetScaler-
autoscaled serving fleet (scale-to-zero through the wake-on-arrival
cold-start path), training-job churn on the control plane, and injected
faults — replica kills, a pod hang, a torn checkpoint — with ONE report
(`monitoring.build_slo_report` + `SLOMonitor.evaluate()` over the
calibrated `default_slos()` set) gating goodput ratio, the restart-
overhead budget, p99 TTFT, and zero dropped requests. Lands in tier-1
as the `prod_day` cpu-proxy workload (profiling/cpu_proxy.py), with
`KFTPU_PROF_CHAOS="scaler_freeze:1"` as the falsifiable teeth: a scaler
that stops reacting while the waves continue must fire the SLO
burn-rate alert and fail the gate. docs/autoscaling.md is the guide.
"""

from kubeflow_tpu.soak.scenario import (
    SoakConfig,
    calibrated_default_slos,
    run_prod_day,
)

__all__ = [
    "SoakConfig",
    "calibrated_default_slos",
    "run_prod_day",
]
