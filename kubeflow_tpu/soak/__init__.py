"""kftpu-storm — the production-day soak (ROADMAP item 6).

One seeded, tick-driven "day in production" composing every subsystem
the platform has grown: diurnal traffic waves against a FleetScaler-
autoscaled serving fleet (scale-to-zero through the wake-on-arrival
cold-start path), training-job churn on the control plane, and injected
faults — replica kills, a pod hang, a torn checkpoint — with ONE report
(`monitoring.build_slo_report` + `SLOMonitor.evaluate()` over the
calibrated `default_slos()` set) gating goodput ratio, the restart-
overhead budget, p99 TTFT, and zero dropped requests. Lands in tier-1
as the `prod_day` cpu-proxy workload (profiling/cpu_proxy.py), with
`KFTPU_PROF_CHAOS="scaler_freeze:1"` as the falsifiable teeth: a scaler
that stops reacting while the waves continue must fire the SLO
burn-rate alert and fail the gate. docs/autoscaling.md is the guide.

kftpu-chipsched adds the diurnal storm (`run_diurnal_storm`): the same
day re-run on a chip-CONSTRAINED cluster where peak serving demand
cannot fit without preempting batch training through the shared
ChipScheduler ledger — real JAXJob gangs evicted via the gang-restart
path, resumed when the trough frees chips, gated on preemption-to-
resume latency, zero serving SLO violations, and a batch goodput
floor. `KFTPU_PROF_CHAOS="sched_freeze:1"` (the ledger stops granting)
is its teeth. docs/scheduler.md is the guide.

kftpu-net re-composes the day on REAL pods (`run_prod_day_pods`): a
spawn_pod TCP fleet where the kills are SIGKILLs discovered through the
wire, the hang is a SIGSTOP indicted by heartbeat age, and a mid-peak
network partition heals only after the scaler has replaced the victim —
the fenced claim's late deliveries are then read back and refused
(epoch fencing, docs/serving.md), gated on dropped == 0 EXACT and
zero duplicate tokens.
"""

from kubeflow_tpu.soak.scenario import (
    PodSoakConfig,
    SoakConfig,
    StormConfig,
    calibrated_default_slos,
    run_diurnal_storm,
    run_prod_day,
    run_prod_day_pods,
)

__all__ = [
    "PodSoakConfig",
    "SoakConfig",
    "StormConfig",
    "calibrated_default_slos",
    "run_diurnal_storm",
    "run_prod_day",
    "run_prod_day_pods",
]
