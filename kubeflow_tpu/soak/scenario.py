"""The production-day scenario engine — seeded, tick-driven, composed.

Everything here exists elsewhere in isolation: the fleet load harness
(serving/fleet/loadtest.py), the chaos fault plans, the control-plane
storm (profiling/cpu_proxy.py), liveness, the SLO monitor. This module
composes them into ONE drill, because the seams between subsystems only
fail when the subsystems run together (the way PR 1's drills found the
`gang._bind` wedge — at platform scale this time):

  - **diurnal traffic**: a seeded arrival process whose rate follows a
    two-peak day with a mid-afternoon trough to ZERO — the trough forces
    scale-to-zero, the evening peak forces the wake-on-arrival cold
    start, and the ramps force real scale-up/scale-down decisions;
  - **the autoscaled fleet**: a FleetScaler (serving/fleet/scaler.py)
    drives replica count from `demand_replicas_burn` each tick — every
    scale event in the drill is the closed loop acting, not a script;
  - **training churn**: a real FakeCluster + controller + status-write
    buffer runs job churn beside the traffic (pods to Running through
    the real informer→workqueue path), with seeded pod kills whose
    re-convergence cost is the restart-overhead budget, and one torn
    checkpoint exercised through the verified-restore fallback;
  - **faults**: seeded replica kills (zero-drop requeue under an
    autoscaling fleet), one pod hang (a replica silently stops ticking;
    the scaler's liveness watch must declare it and politely kill it),
    and the torn checkpoint above;
  - **one report**: `build_slo_report` + `SLOMonitor.evaluate()` over
    `calibrated_default_slos()` — the default objective set with its
    latency thresholds re-anchored to in-run healthy measurements so
    the gate is machine-speed invariant (the serve_fleet trick).

Ticks are the schedule unit (arrivals, faults, scaler cadence); wall
time is real, so the TSDB and the SLO windows behave exactly as in
production. docs/autoscaling.md walks the whole loop.
"""

from __future__ import annotations

import dataclasses
import random
import shutil
import tempfile
import time
from dataclasses import dataclass

import numpy as np

from kubeflow_tpu.monitoring import (
    SLOMonitor,
    TimeSeriesStore,
    default_slos,
)
from kubeflow_tpu.serving.fleet import (
    FleetOverloaded,
    FleetRouter,
    FleetScaler,
    PagedKVPool,
    ScalerConfig,
    make_prompts,
)

#: The TTFT objective is thresholded in SCHEDULER TICKS, not wall
#: seconds: one loop tick advances every live replica one engine tick —
#: the simulated-concurrency unit — so a request's (first-token tick −
#: arrival tick) is machine-speed invariant AND fleet-size fair. Wall
#: seconds would invert reality here: serializing N engines in one loop
#: makes a BIGGER fleet slower per tick, so a frozen one-replica fleet
#: looked FASTER on the wall clock than the healthy autoscaled one
#: (found driving the freeze teeth). A reacting scaler holds queues to
#: a few ticks (healthy p99 ~5 with the threshold at 16); a frozen
#: scaler under the same waves runs a peak-long backlog (mean ~16,
#: p99 ~38, bad fraction ~10x the 5% budget) — the teeth margin
#: test_prof_gate pins both sides.
TTFT_SLO_TICKS = 16.0
#: looser than serve_fleet's 1.4: soak decode dispatches interleave
#: with churn controller threads and the scaler — this drill's decode
#: teeth live in serve_fleet/serve_disagg; here the objective must stay
#: alert-quiet through an autoscaled noisy day
DECODE_SLO_HEADROOM = 3.0

#: the churn leg's pod ownership label
SOAK_LABEL = "kubeflow-tpu.org/soak-train"


@dataclass(frozen=True)
class SoakConfig:
    """One day, in ticks. The defaults are sized so the whole drill —
    warmup, day, drain — runs in tens of seconds on CPU while still
    forcing every transition: multi-replica peaks, a scale-to-zero
    trough, a wake-on-arrival cold start, kills, one hang, and churn."""

    seed: int = 17
    day_ticks: int = 240
    #: diurnal peaks in arrivals/tick (trough is 0 by construction):
    #: both sit well past one replica's ~0.5 req/tick capacity and
    #: under the max_replicas fleet's — a frozen scaler MUST fall
    #: behind, a reacting one must keep up
    peak1_rate: float = 1.6
    peak2_rate: float = 1.8
    #: serving geometry (the serve_fleet shape, slightly smaller)
    rows: int = 3
    prompt_body: int = 4
    shared_prefix: int = 4
    new_tokens: int = 4
    block: int = 4
    chunk: int = 4
    max_replicas: int = 5
    #: seeded fault schedule, as day fractions
    kill_at: tuple = (0.33, 0.72)
    hang_at: float = 0.62
    hang_ticks: int = 10
    #: scaler cadence knobs (evaluations == ticks here)
    scale_up_cooldown_evals: int = 2
    scale_down_stable_evals: int = 8
    idle_to_zero_evals: int = 12
    drain_grace_evals: int = 8
    hang_detect_evals: int = 5
    #: SLO monitor evaluation cadence (ticks) — the scaler's burn-aware
    #: demand reads the monitor's last pass (the PR-12 contract), so
    #: this is also how fast a latency burn can raise the fleet
    slo_eval_every: int = 3
    #: control-plane churn: jobs arriving through the day
    churn_jobs: int = 6
    churn_pods_per_job: int = 2
    churn_job_ticks: int = 40
    churn_kill_at: tuple = (0.4, 0.66)
    #: post-day drain bound (a frozen scaler serves the whole backlog
    #: through one replica — bounded, not infinite)
    max_drain_ticks: int = 6000


def arrival_rate(tick: int, cfg: SoakConfig) -> float:
    """The diurnal profile: morning ramp to peak 1, a trough to ZERO
    (scale-to-zero territory), an evening peak 2, then night. Returns
    arrivals per tick."""
    f = tick / cfg.day_ticks
    if f < 0.04:
        return 0.25  # early trickle: first request wakes nothing (one
        # replica is up) but calibrates the service rate
    if f < 0.22:
        return 0.3 + (cfg.peak1_rate - 0.3) * (f - 0.04) / 0.18
    if f < 0.34:
        return cfg.peak1_rate
    if f < 0.40:
        return cfg.peak1_rate * (0.40 - f) / 0.06
    if f < 0.58:
        return 0.0  # the trough: the fleet must reach zero here
    if f < 0.66:
        return cfg.peak2_rate * (f - 0.58) / 0.08
    if f < 0.84:
        return cfg.peak2_rate
    return 0.0  # night


def calibrated_default_slos(ttft_threshold_s: float,
                            decode_threshold_s: float):
    """`default_slos()` with the two latency thresholds re-anchored to
    in-run healthy measurements (everything else — names, kinds,
    budgets, windows, the goodput ratio threshold and the zero-drop
    contract — stays the platform default). Absolute CPU latencies are
    machine-dependent; the OBJECTIVE SET is not."""
    out = []
    for cfg in default_slos():
        if cfg.name == "serving_ttft_p99":
            cfg = dataclasses.replace(cfg, threshold=ttft_threshold_s)
        elif cfg.name == "serving_decode_tick":
            cfg = dataclasses.replace(cfg, threshold=decode_threshold_s)
        out.append(cfg)
    return tuple(out)


# --------------------------------------------------------------- churn leg


class _ChurnLeg:
    """Training-job churn on a real control plane: labeled pods driven
    to Running by a real controller (informer → keyed workqueue →
    status-write buffer), jobs arriving/completing through the day,
    seeded pod kills restarting incarnations. goodput(tick) is the
    running/desired pod ratio — 1.0 converged, dented by kills — and
    the dents sum into the restart-overhead budget."""

    def __init__(self, cfg: SoakConfig, rng: random.Random):
        from kubeflow_tpu.controller.base import ControllerBase
        from kubeflow_tpu.controller.fakecluster import (
            FakeCluster,
            PodPhase,
        )
        from kubeflow_tpu.controller.statusbuffer import StatusWriteBuffer

        self.cfg = cfg
        self.cluster = FakeCluster()
        self.buffer = StatusWriteBuffer(self.cluster, kind="pods")
        self._phase_running = PodPhase.RUNNING
        self._phase_pending = PodPhase.PENDING
        buffer = self.buffer

        class ChurnController(ControllerBase):
            ERROR_EVENT_KIND = "pods"
            WATCH_SELECTORS = {"pods": {SOAK_LABEL: None}}

            def kind_filter(self, etype, kind, obj):
                if kind == "pods" and SOAK_LABEL in obj.metadata.labels:
                    return obj.key
                return None

            def resync_keys(self):
                return ()

            def reconcile(self, key):
                pod = self.cluster.get("pods", key)
                if pod is None or pod.status.phase != PodPhase.PENDING:
                    return None

                def to_running(p):
                    if p.status.phase != PodPhase.PENDING:
                        return False
                    p.status.phase = PodPhase.RUNNING
                    p.status.node = "soak-node"

                buffer.write(key, pod.metadata.uid, to_running)
                return None

        self.ctrl = ChurnController(self.cluster, "soaktrain", workers=1)
        # job j -> (create tick, complete tick); spread across the day,
        # every job finishing inside it
        span = cfg.day_ticks - cfg.churn_job_ticks - 5
        self.schedule = sorted(
            rng.randrange(1, max(span, 2)) for _ in range(cfg.churn_jobs))
        self.kill_ticks = sorted(
            int(f * cfg.day_ticks) for f in cfg.churn_kill_at)
        self._live: dict[int, int] = {}  # job -> completion tick
        self._next_job = 0
        self._restarted = 0
        self.pod_ticks = 0
        self.overhead_pod_ticks = 0
        self.goodput_samples: list[float] = []

    def start(self) -> "_ChurnLeg":
        self.ctrl.start()
        return self

    def _pod(self, job: int, idx: int):
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.controller.fakecluster import Pod

        return Pod(metadata=ObjectMeta(
            name=f"soak-{job:02d}-{idx}", labels={SOAK_LABEL: "1"}))

    def step(self, tick: int) -> float:
        """Advance the churn by one tick; returns the goodput sample
        (1.0 when no training work is live)."""
        cfg = self.cfg
        while (self._next_job < len(self.schedule)
               and self.schedule[self._next_job] <= tick):
            job = self._next_job
            for i in range(cfg.churn_pods_per_job):
                self.cluster.create("pods", self._pod(job, i))
            self._live[job] = tick + cfg.churn_job_ticks
            self._next_job += 1
        for job, done in list(self._live.items()):
            if done <= tick:
                for i in range(cfg.churn_pods_per_job):
                    try:
                        self.cluster.delete(
                            "pods", f"default/soak-{job:02d}-{i}")
                    except KeyError:
                        pass
                del self._live[job]
        if self.kill_ticks and self.kill_ticks[0] <= tick and self._live:
            # the fault: kill one running pod of a live job — delete +
            # recreate is the restart incarnation; reconvergence cost
            # lands in the overhead ledger below
            self.kill_ticks.pop(0)
            job = next(iter(self._live))
            key = f"default/soak-{job:02d}-0"
            try:
                self.cluster.delete("pods", key)
                self.cluster.create("pods", self._pod(job, 0))
                self._restarted += 1
            except KeyError:
                pass
        desired = len(self._live) * cfg.churn_pods_per_job
        if desired == 0:
            return 1.0
        running = len(self.cluster.list(
            "pods",
            lambda p: SOAK_LABEL in p.metadata.labels
            and p.status.phase == self._phase_running))
        running = min(running, desired)
        self.pod_ticks += desired
        self.overhead_pod_ticks += desired - running
        sample = running / desired
        self.goodput_samples.append(sample)
        return sample

    def finish(self) -> dict:
        self.ctrl.stop()
        self.buffer.close()
        mean = (sum(self.goodput_samples) / len(self.goodput_samples)
                if self.goodput_samples else 1.0)
        return {
            "jobs": len(self.schedule),
            "pod_restarts": self._restarted,
            "goodput_mean": round(mean, 4),
            "goodput_min": round(min(self.goodput_samples, default=1.0),
                                 4),
            "restart_overhead_frac": round(
                self.overhead_pod_ticks / max(self.pod_ticks, 1), 4),
        }


def _torn_checkpoint() -> dict:
    """The torn-checkpoint seam, composed into the day: save two
    verified steps, corrupt the newest (the chaos torn-save shape),
    and prove restore falls back to the previous VERIFIED step with the
    corrupt one quarantined (docs/health.md)."""
    from kubeflow_tpu.chaos import corrupt_newest_checkpoint
    from kubeflow_tpu.train.checkpoint import Checkpointer

    d = tempfile.mkdtemp(prefix="kftpu-soak-ckpt-")
    try:
        ck = Checkpointer(d, max_to_keep=4, async_save=False)
        x = np.arange(8, dtype=np.float32)
        ck.save(1, {"x": x})
        ck.save(2, {"x": x * 2})
        corrupted = corrupt_newest_checkpoint(d)
        step, restored = ck.restore_latest({"x": x})
        ck.close()
        ok = (corrupted == 2 and step == 1
              and bool(np.allclose(restored["x"], x)))
        return {"fallback_ok": ok, "corrupted_step": corrupted,
                "restored_step": step}
    finally:
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------- the day


def run_prod_day(cfg: SoakConfig | None = None, frozen: bool = False,
                 tracer=None) -> dict:
    """Run one production day (module docstring). `frozen=True` is the
    scaler_freeze chaos mode: the scaler evaluates but acts on nothing
    while the waves continue — the SLO burn alert must catch it.
    Returns the raw drill record (seconds + counts); the cpu-proxy
    `prod_day` workload turns it into the anchored gate record."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.gpt import GPTConfig, GPTLM
    from kubeflow_tpu.monitoring.report import build_slo_report_from_spans
    from kubeflow_tpu.serving.continuous import ContinuousBatcher
    from kubeflow_tpu.tracing import Tracer

    cfg = cfg or SoakConfig()
    rng = random.Random(f"kftpu-soak-{cfg.seed}")
    prompt_len = cfg.shared_prefix + cfg.prompt_body
    gpt_cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=2, mlp_dim=128, dropout_rate=0.0,
                        max_len=prompt_len + cfg.new_tokens + 18)
    model = GPTLM(gpt_cfg)
    variables = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    pool = PagedKVPool(block_size=cfg.block, capacity_blocks=1024)
    tsdb = TimeSeriesStore(capacity_per_series=4096)
    tracer = tracer if tracer is not None else Tracer(
        capacity=16384, service="prod_day")
    warm_prompt = make_prompts(1, seed=cfg.seed + 90,
                               vocab=gpt_cfg.vocab_size,
                               prompt_len=cfg.prompt_body,
                               shared_prefix=cfg.shared_prefix)[0]

    def build_warm_engine():
        # build + WARM before serving (the readiness-probe contract):
        # chunk prefill, decode step, splice, and the pool-match
        # suffix-1 shape all compile here, so a replica never serves
        # its first request through XLA. Monitoring attaches via
        # router.add_replica (_wire_engine), AFTER the warm traffic —
        # warm ticks carry compile time and must not poison the SLO
        # series.
        eng = ContinuousBatcher(
            model, variables, max_rows=cfg.rows,
            default_max_new_tokens=cfg.new_tokens,
            paged_kv=pool, prefill_chunk=cfg.chunk)
        for _ in range(2):
            eng.submit(warm_prompt, max_new_tokens=2)
            eng.run_until_idle()
        return eng

    # standby pool: every replica the day can consume is compiled and
    # warmed BEFORE the day starts — the AOT / restart-warm cold-start
    # contract (serving/aot.py, utils/compile_cache.py): production
    # scale-up cost is scheduling + activation, not XLA, so the soak's
    # cold starts must not be dominated by per-engine jit either. The
    # factory pops a standby and GRACEFULLY-drained engines recycle
    # back (the scaler's on_release hook) — only kills and hangs
    # consume the pool for good, so it is sized for max concurrency
    # plus one replacement per injected fault. An exhausted pool
    # rebuilds on demand; that genuinely slow cold start shows in the
    # EWMA.
    standby = [build_warm_engine()
               for _ in range(cfg.max_replicas + len(cfg.kill_at) + 1)]
    in_day_builds = [0]

    def engine_factory():
        if standby:
            return standby.pop()
        in_day_builds[0] += 1
        return build_warm_engine()

    # ---- the fleet: one warm replica up, scaler owning the rest
    first = engine_factory()
    router = FleetRouter([("scaled-base", first)], max_requeues=5,
                         tracer=tracer)

    # ---- in-run anchors: healthy decode tick through the SAME tsdb
    # hook the monitored samples use (the serve_fleet trick), measured
    # on full rows before any monitoring attaches
    for p in make_prompts(cfg.rows, seed=cfg.seed + 91,
                          vocab=gpt_cfg.vocab_size,
                          prompt_len=cfg.prompt_body,
                          shared_prefix=cfg.shared_prefix):
        first.submit(p, max_new_tokens=cfg.new_tokens + 12)
    for _ in range(cfg.rows * (prompt_len // cfg.chunk + 2)):
        first.tick()
        if not first._pending and all(first._rows):
            break
    anchor_tsdb = TimeSeriesStore()
    saved_tsdb, first.tsdb = first.tsdb, anchor_tsdb
    for _ in range(12):
        first.tick()
    first.tsdb = saved_tsdb
    healthy_tick = sorted(
        v for _, v in anchor_tsdb.window("serving.decode_tick_s",
                                         3600.0))
    healthy_tick = healthy_tick[len(healthy_tick) // 2]
    first.run_until_idle()
    # monitoring attaches only now: anchor + warm traffic stayed out of
    # the SLO series; scale-up replicas inherit both via add_replica
    router.wire_monitoring(tsdb=tsdb)

    # admission shedding is LAST-RESORT here (threshold far past the
    # demand signal's reaction point — shedding hides latency from the
    # TTFT objective, the blindspot this drill's first runs exposed);
    # the demand signal runs on the explicit working-set capacity
    # target, and the TTFT OBJECTIVE is thresholded in ticks (module
    # comment)
    admission_slo_s = 500.0 * healthy_tick
    decode_threshold = DECODE_SLO_HEADROOM * healthy_tick
    router.ttft_slo_s = admission_slo_s
    router.retry_after_s = max(8.0 * healthy_tick, 1e-4)
    router.demand_tokens_per_replica = float(
        cfg.rows * (prompt_len + cfg.new_tokens))
    monitor = SLOMonitor(tsdb, calibrated_default_slos(
        TTFT_SLO_TICKS, decode_threshold))
    scaler = FleetScaler(
        router, engine_factory,
        ScalerConfig(
            min_replicas=0, max_replicas=cfg.max_replicas,
            scale_up_cooldown_evals=cfg.scale_up_cooldown_evals,
            scale_down_stable_evals=cfg.scale_down_stable_evals,
            idle_to_zero_evals=cfg.idle_to_zero_evals,
            drain_grace_evals=cfg.drain_grace_evals,
            hang_detect_evals=cfg.hang_detect_evals),
        monitor=monitor, tracer=tracer,
        on_release=standby.append)
    if frozen:
        scaler.freeze()

    # ---- seeded schedules
    prompts = make_prompts(
        int(cfg.day_ticks * max(cfg.peak1_rate, cfg.peak2_rate)) + 64,
        seed=cfg.seed, vocab=gpt_cfg.vocab_size,
        prompt_len=cfg.prompt_body, shared_prefix=cfg.shared_prefix)
    kill_ticks = sorted(int(f * cfg.day_ticks) for f in cfg.kill_at)
    hang_tick = int(cfg.hang_at * cfg.day_ticks)
    churn = _ChurnLeg(cfg, rng).start()

    handles: dict[int, object] = {}
    retries: list[tuple[int, int]] = []  # (due tick, prompt idx)
    shed_retries = 0
    recent_ttfts: list[float] = []   # wall seconds (informational)
    ttft_ticks: list[int] = []       # scheduler ticks (the SLO unit)
    arrival_tick: dict[int, int] = {}
    first_tok_tick: dict[int, int] = {}
    retry_wait_ticks: dict[int, int] = {}
    cur_tick = [0]
    collected: set[int] = set()
    hung: dict[str, int] = {}  # replica name -> resume tick
    n_submitted = 0
    kills_done = 0
    hang_done = False
    replicas_peak = 1
    ckpt = {}

    def _note_first_token(idx: int):
        def cb(_freq, _tok):
            # client-perceived first token, in scheduler ticks: the
            # `delivered` high-water mark guarantees this fires once
            # per position even across requeue re-decodes
            first_tok_tick.setdefault(idx, cur_tick[0])
        return cb

    def submit(idx: int, tick: int) -> None:
        nonlocal shed_retries
        try:
            handles[idx] = router.submit(
                prompts[idx], max_new_tokens=cfg.new_tokens,
                on_token=_note_first_token(idx))
            # TTFT counts from the SUCCESSFUL admission (the LoadReport
            # contract: client Retry-After backoff is accounted apart
            # from TTFT, never folded into it)
            arrival_tick[idx] = tick
        except FleetOverloaded as exc:
            # the client honors Retry-After (serving/client.py contract)
            # in tick units: back off proportionally, re-dial, never
            # give up — "dropped" means dropped, not "shed and tired"
            shed_retries += 1
            delay = min(max(1, round(exc.retry_after_s
                                     / max(healthy_tick, 1e-9))), 25)
            retry_wait_ticks[idx] = retry_wait_ticks.get(idx, 0) + delay
            retries.append((tick + delay, idx))

    def one_tick(tick: int, arrivals: int) -> None:
        nonlocal n_submitted, kills_done, hang_done, replicas_peak
        cur_tick[0] = tick
        # faults first (the drill order: the world breaks, then serves)
        if kill_ticks and kill_ticks[0] <= tick:
            admittable = [r for r in router._admittable()
                          if r.name not in hung]
            if len(admittable) >= 2:
                kill_ticks.pop(0)
                kills_done += 1
                router.kill_replica(
                    admittable[rng.randrange(len(admittable))].name)
        if not hang_done and tick >= hang_tick:
            admittable = [r for r in router._admittable()
                          if r.name not in hung]
            if admittable:
                victim = admittable[0]
                hung[victim.name] = tick + cfg.hang_ticks
                hang_done = True
        for name, until in list(hung.items()):
            if until <= tick:
                del hung[name]  # SIGCONT: the replica ticks again
        # arrivals + due retries
        for _ in range(arrivals):
            if n_submitted < len(prompts):
                submit(n_submitted, tick)
                n_submitted += 1
        for due, idx in list(retries):
            if due <= tick:
                retries.remove((due, idx))
                submit(idx, tick)
        # serve: one round-robin pass over live, un-hung replicas
        # (a hung replica is SIGSTOPped — alive, silent)
        for rep in list(router.replicas):
            if rep.alive and rep.name not in hung:
                rep.engine.tick()
        # the monitoring plane: one TTFT sample per COMPLETED request,
        # in scheduler ticks (module comment — the machine-invariant,
        # fleet-size-fair latency unit), counted from the SUCCESSFUL
        # admission (the LoadReport contract: client Retry-After
        # backoff is accounted apart, in retry_wait_ticks — shed
        # volume is its own signal in the record, never folded into
        # TTFT). The burn math then reads "fraction of requests over
        # the threshold" against the 5% budget — the per-event form of
        # the p99 objective; a single slow request is one bad sample,
        # never a sticky window artifact.
        for idx, h in list(handles.items()):
            if idx not in collected and h.done.is_set() \
                    and h.error is None:
                collected.add(idx)
                if h.ttft_s is not None:
                    recent_ttfts.append(h.ttft_s)
                if idx in first_tok_tick:
                    dt = first_tok_tick[idx] - arrival_tick[idx]
                    ttft_ticks.append(dt)
                    tsdb.record(
                        'kftpu_fleet_ttft_seconds{quantile="0.99"}',
                        float(dt))
        tsdb.record("kftpu_fleet_requests_failed_total",
                    router.metrics["requests_failed_total"])
        tsdb.record("kftpu_prof_goodput_ratio", churn.step(tick))
        if tick % cfg.slo_eval_every == 0:
            monitor.evaluate()  # the burn the scaler's demand reads
        scaler.evaluate()
        replicas_peak = max(replicas_peak, len(router._admittable()))

    t0 = time.perf_counter()
    tick = 0
    try:
        for tick in range(cfg.day_ticks):
            if not ckpt and tick >= cfg.day_ticks // 2:
                ckpt = _torn_checkpoint()  # the mid-day torn save
            one_tick(tick, _arrivals(arrival_rate(tick, cfg), rng))
        # night drain: no new arrivals; retries and backlog must all
        # complete (a frozen scaler pays this through one replica)
        while tick < cfg.day_ticks + cfg.max_drain_ticks:
            tick += 1
            if (not retries
                    and all(h.done.is_set() for h in handles.values())
                    and len(handles) + len(retries) >= n_submitted):
                break
            one_tick(tick, 0)
    finally:
        wall_s = time.perf_counter() - t0
        churn_stats = churn.finish()
        for rep in router.replicas:
            rep.engine.stop()

    # every submitted index ends in exactly one place: a handle (served
    # or failed) or the retry list (shed and never re-admitted) — both
    # non-completions count as drops, nothing double-counts
    dropped = sum(
        1 for h in handles.values()
        if h.error is not None or not h.done.is_set()
    ) + len(retries)

    # ---- THE report: one build path with /debug/slo and the CLI
    report = build_slo_report_from_spans(tracer.snapshot(),
                                         monitor=monitor)
    states = {s["name"]: s for s in report["slos"]}
    worst_burn = 0.0
    for name in ("serving_ttft_p99", "serving_decode_tick",
                 "serving_zero_drop"):
        rates = states.get(name, {}).get("burn_rates", {})
        if rates:
            worst_burn = max(worst_burn, max(rates.values()))
    def _p99(values):
        s = sorted(values)
        return s[min(len(s) - 1, int(len(s) * 0.99))] if s else 0.0

    ttft_p99 = _p99(recent_ttfts)
    ttft_p99_ticks = _p99(ttft_ticks)
    decode_samples = sorted(
        v for _, v in tsdb.window("serving.decode_tick_s", 10 ** 6))
    m = scaler.metrics

    return {
        "seed": cfg.seed,
        "frozen": frozen,
        "ticks": tick + 1,
        "day_ticks": cfg.day_ticks,
        "wall_s": round(wall_s, 3),
        "n_requests": n_submitted,
        "completed": len(collected),
        "dropped": dropped,
        "shed_retries": shed_retries,
        "requeued": router.metrics["requests_requeued_total"],
        "resumed": router.metrics["requeues_resumed_total"],
        "retry_wait_ticks_p99": _p99(list(retry_wait_ticks.values())),
        "kills_injected": kills_done,
        "hang_injected": hang_done,
        "replicas_peak": replicas_peak,
        "in_day_engine_builds": in_day_builds[0],
        "scaler": dict(m),
        "scale_to_zero_reached": m["scale_to_zero_total"] >= 1,
        "recovered_from_zero": m["scale_from_zero_total"] >= 1,
        "cold_start_ewma_s": round(scaler.cold_start_ewma_s, 4),
        "ttft_p99_s": round(ttft_p99, 6),
        "ttft_p99_ticks": float(ttft_p99_ticks),
        "ttft_mean_ticks": round(
            sum(ttft_ticks) / len(ttft_ticks), 3) if ttft_ticks else 0.0,
        "ttft_max_ticks": float(max(ttft_ticks, default=0)),
        "ttft_bad_frac": round(
            sum(1 for t in ttft_ticks if t > TTFT_SLO_TICKS)
            / max(len(ttft_ticks), 1), 4),
        "ttft_threshold_ticks": TTFT_SLO_TICKS,
        "admission_slo_s": round(admission_slo_s, 6),
        "healthy_tick_s": round(healthy_tick, 6),
        "decode_tick_s": round(
            decode_samples[len(decode_samples) // 2], 6)
        if decode_samples else 0.0,
        "churn": churn_stats,
        "ckpt": ckpt,
        "slo": {
            "alerts": [a["slo"] for a in report["alerts"]],
            "worst_serving_burn": round(worst_burn, 4),
            "states": {
                name: {"fired": st["fired"],
                       "burn_rates": st["burn_rates"],
                       "samples": st["samples"]}
                for name, st in states.items()
            },
        },
        "report": {
            "requests": report["requests"],
            "tsdb": report["tsdb"],
        },
    }


def _arrivals(rate: float, rng: random.Random) -> int:
    """Seeded per-tick arrival count for a fractional rate."""
    n = int(rate)
    if rng.random() < rate - n:
        n += 1
    return n


# ------------------------------------------------------- the diurnal storm


@dataclass(frozen=True)
class StormConfig(SoakConfig):
    """The chip-constrained day (docs/scheduler.md): the prod-day waves
    re-run on a cluster where peak serving demand CANNOT fit without
    preempting batch training. 12 chips, 4 per slice: two 4-chip batch
    gangs hold 8, the base serving replica 1 — three free. The evening
    peak demands more replicas than the free pool covers, so the shared
    ledger's preemption-then-grant evicts the youngest (borrowed) gang;
    the trough and the night release chips and the gang gang-restarts
    back in. Every number below is sized so both transitions MUST
    happen on the seeded schedule."""

    capacity_chips: int = 12
    chips_per_slice: int = 4
    batch_gangs: int = 2
    batch_workers: int = 2
    #: 2x2 = 4 chips = one whole slice per gang
    batch_topology: str = "2x2"
    #: higher evening peak + heavier replicas than the free pool:
    #: serving claims 2 chips per replica, so only TWO replicas fit
    #: beside the gangs (8 + 2x2 = 12) — the third claim of either
    #: peak must evict a batch gang (preemption-then-grant), and
    #: max_replicas is reachable only over preempted chips
    peak2_rate: float = 3.4
    serving_chips_per_replica: int = 2
    max_replicas: int = 4
    #: post-drain bound on waiting for the evicted gang's rebind
    resume_wait_ticks: int = 2000


class _BatchGangLeg:
    """Batch training gangs on a real control plane, drawing from the
    SAME chip ledger as the serving fleet: a FakeCluster + GangScheduler
    + JobController stack whose jobs reserve whole slices through
    `ChipScheduler.claim_gang`. Pods are never started (no runtime —
    the leg measures scheduling, not training): a gang is "running" when
    its podgroup is admitted and bound. A scheduler eviction marks the
    pods FAILED with the PREEMPTED exit class; the job controller's
    gang-restart path recreates them and the gang re-admits when the
    serving fleet releases chips — preempt-to-resume is measured in
    ticks by polling the podgroup phase."""

    def __init__(self, cfg: StormConfig, tracer, workdir: str):
        import os

        from kubeflow_tpu.controller.fakecluster import FakeCluster
        from kubeflow_tpu.controller.gang import (
            GangScheduler,
            topology_chips,
        )
        from kubeflow_tpu.controller.jobcontroller import JobController
        from kubeflow_tpu.scheduler.chipsched import ChipScheduler

        self.cfg = cfg
        self.cluster = FakeCluster()
        self.cluster.capacity_chips = cfg.capacity_chips
        self.cluster.tracer = tracer
        #: THE shared inventory: the gang scheduler admits through it
        #: and the FleetScaler claims replica chips from it
        self.ledger = ChipScheduler(
            capacity_fn=lambda: self.cluster.capacity_chips,
            tracer_fn=lambda: self.cluster.tracer,
            chips_per_slice=cfg.chips_per_slice)
        self.gang = GangScheduler(self.cluster, chipsched=self.ledger)
        self.jc = JobController(
            self.cluster, workers=1,
            heartbeat_dir=os.path.join(workdir, "heartbeats"),
            compile_cache_dir=os.path.join(workdir, "compile-cache"))
        self.gang_chips = topology_chips(cfg.batch_topology)
        self.job_keys = [
            f"default/storm-batch-{i}" for i in range(cfg.batch_gangs)]
        self._bound: dict[str, bool] = {}
        self._evicted_at: dict[str, int] = {}
        self.preemptions_seen = 0
        self.resume_ticks: list[int] = []
        self.goodput_samples: list[float] = []

    def start(self) -> "_BatchGangLeg":
        from kubeflow_tpu.api.common import (
            ContainerSpec,
            ObjectMeta,
            PodTemplateSpec,
            ReplicaSpec,
            RestartPolicy,
            RunPolicy,
            SchedulingPolicy,
        )
        from kubeflow_tpu.api.jobs import (
            JAXJob,
            JAXJobSpec,
            REPLICA_WORKER,
        )

        self.jc.start()
        self.gang.start()
        for i in range(self.cfg.batch_gangs):
            job = JAXJob(
                metadata=ObjectMeta(name=f"storm-batch-{i}"),
                spec=JAXJobSpec(
                    replica_specs={REPLICA_WORKER: ReplicaSpec(
                        replicas=self.cfg.batch_workers,
                        # the preemption contract: exit 143 (128+SIGTERM)
                        # is retryable BY CONSTRUCTION under ExitCode
                        restart_policy=RestartPolicy.EXIT_CODE,
                        template=PodTemplateSpec(
                            container=ContainerSpec(
                                command=["python", "-c", "pass"])))},
                    run_policy=RunPolicy(
                        backoff_limit=64,
                        scheduling_policy=SchedulingPolicy(
                            slice_topology=self.cfg.batch_topology)),
                ))
            self.cluster.create("jobs", job)
        return self

    def wait_bound(self, timeout_s: float = 30.0) -> None:
        """Block until every gang is admitted (the pre-day steady
        state; the storm's transitions are measured from here)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.gang._try_schedule_safe()
            if all(self._pg_bound(k) for k in self.job_keys):
                return
            time.sleep(0.01)
        raise TimeoutError(
            f"batch gangs failed to bind: "
            f"{[(k, self._pg_bound(k)) for k in self.job_keys]}")

    def _pg_bound(self, key: str) -> bool:
        pg = self.cluster.get("podgroups", key)
        return pg is not None and pg.phase == "Running"

    def nudge(self) -> None:
        """One synchronous scheduling pass — the tick loop calls this
        after the scaler may have released chips, so a rebind lands on
        the tick that freed the capacity (the gang thread's 0.5s poll
        would smear the resume latency across wall time)."""
        self.gang._try_schedule_safe()

    def step(self, tick: int) -> float:
        """Poll gang state; returns the chips-weighted goodput sample
        (bound batch chips / total batch chips)."""
        for key in self.job_keys:
            bound = self._pg_bound(key)
            was = self._bound.get(key, False)
            if was and not bound:
                # the only unbind in this leg is a scheduler eviction
                self._evicted_at[key] = tick
                self.preemptions_seen += 1
            elif bound and not was and key in self._evicted_at:
                self.resume_ticks.append(
                    tick - self._evicted_at.pop(key))
            self._bound[key] = bound
        total = self.gang_chips * len(self.job_keys)
        sample = (sum(self.gang_chips for k in self.job_keys
                      if self._bound.get(k)) / total) if total else 1.0
        self.goodput_samples.append(sample)
        return sample

    def all_bound(self) -> bool:
        return all(self._bound.get(k) for k in self.job_keys)

    def finish(self) -> dict:
        self.gang.stop()
        self.jc.stop()
        restarts = {}
        for key in self.job_keys:
            job = self.cluster.get("jobs", key)
            restarts[key] = job.status.restart_count if job else -1
        mean = (sum(self.goodput_samples) / len(self.goodput_samples)
                if self.goodput_samples else 1.0)
        return {
            "gangs": len(self.job_keys),
            "gang_chips": self.gang_chips,
            "preemptions_seen": self.preemptions_seen,
            "resume_ticks": list(self.resume_ticks),
            "resumed": len(self.resume_ticks),
            "restart_counts": restarts,
            "goodput_mean": round(mean, 4),
            "goodput_min": round(
                min(self.goodput_samples, default=1.0), 4),
        }


def run_diurnal_storm(cfg: StormConfig | None = None,
                      frozen: bool = False, tracer=None) -> dict:
    """One chip-constrained production day (StormConfig docstring):
    the prod-day serving waves with the fleet's replica chips claimed
    from the SAME ledger two batch training gangs occupy. The peaks
    force preemption-then-grant (a batch gang is evicted through the
    gang-restart path), the trough and the night force the resume —
    gated on p99 TTFT, zero drops, ZERO serving SLO violations,
    preempt-to-resume latency in ticks, and the batch goodput floor.
    `frozen=True` is the sched_freeze chaos mode: the ledger stops
    granting (admission-only outage — releases still work) while the
    waves continue, so the fleet is pinned at one replica through both
    peaks and the SLO burn alert must catch it."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.gpt import GPTConfig, GPTLM
    from kubeflow_tpu.monitoring.report import build_slo_report_from_spans
    from kubeflow_tpu.serving.continuous import ContinuousBatcher
    from kubeflow_tpu.tracing import Tracer

    cfg = cfg or StormConfig()
    rng = random.Random(f"kftpu-storm-{cfg.seed}")
    prompt_len = cfg.shared_prefix + cfg.prompt_body
    gpt_cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=2, mlp_dim=128, dropout_rate=0.0,
                        max_len=prompt_len + cfg.new_tokens + 18)
    model = GPTLM(gpt_cfg)
    variables = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    pool = PagedKVPool(block_size=cfg.block, capacity_blocks=1024)
    tsdb = TimeSeriesStore(capacity_per_series=4096)
    tracer = tracer if tracer is not None else Tracer(
        capacity=16384, service="diurnal_storm")
    warm_prompt = make_prompts(1, seed=cfg.seed + 90,
                               vocab=gpt_cfg.vocab_size,
                               prompt_len=cfg.prompt_body,
                               shared_prefix=cfg.shared_prefix)[0]

    def build_warm_engine():
        eng = ContinuousBatcher(
            model, variables, max_rows=cfg.rows,
            default_max_new_tokens=cfg.new_tokens,
            paged_kv=pool, prefill_chunk=cfg.chunk)
        for _ in range(2):
            eng.submit(warm_prompt, max_new_tokens=2)
            eng.run_until_idle()
        return eng

    # ---- the batch leg + THE ledger (fair-share DRF over chips:
    # batch and serving tenants entitled half the cluster each, so the
    # second gang runs on BORROWED chips — the claim an under-share
    # serving peak is entitled to reclaim)
    workdir = tempfile.mkdtemp(prefix="kftpu-storm-")
    leg = _BatchGangLeg(cfg, tracer, workdir)
    ledger = leg.ledger
    ledger.set_shares({"default": 1.0, "serving": 1.0})
    leg.start()
    leg.wait_bound()

    standby = [build_warm_engine() for _ in range(cfg.max_replicas + 1)]
    in_day_builds = [0]

    def engine_factory():
        if standby:
            return standby.pop()
        in_day_builds[0] += 1
        return build_warm_engine()

    # ---- the fleet: one warm replica up, its chip claimed like every
    # scaled replica's will be (the scaler's claim-key convention, so
    # a later drain of the base releases the right claim)
    first = engine_factory()
    router = FleetRouter([("scaled-base", first)], max_requeues=5,
                         tracer=tracer)
    base_grant = ledger.claim_replica(
        "fleet/scaled-base", chips=cfg.serving_chips_per_replica,
        tenant="serving")
    assert base_grant.ok, base_grant

    # ---- in-run anchors (the prod-day trick): healthy decode tick
    # measured before monitoring attaches
    for p in make_prompts(cfg.rows, seed=cfg.seed + 91,
                          vocab=gpt_cfg.vocab_size,
                          prompt_len=cfg.prompt_body,
                          shared_prefix=cfg.shared_prefix):
        first.submit(p, max_new_tokens=cfg.new_tokens + 12)
    for _ in range(cfg.rows * (prompt_len // cfg.chunk + 2)):
        first.tick()
        if not first._pending and all(first._rows):
            break
    anchor_tsdb = TimeSeriesStore()
    saved_tsdb, first.tsdb = first.tsdb, anchor_tsdb
    for _ in range(12):
        first.tick()
    first.tsdb = saved_tsdb
    healthy_tick = sorted(
        v for _, v in anchor_tsdb.window("serving.decode_tick_s",
                                         3600.0))
    healthy_tick = healthy_tick[len(healthy_tick) // 2]
    first.run_until_idle()
    router.wire_monitoring(tsdb=tsdb)

    admission_slo_s = 500.0 * healthy_tick
    decode_threshold = DECODE_SLO_HEADROOM * healthy_tick
    router.ttft_slo_s = admission_slo_s
    router.retry_after_s = max(8.0 * healthy_tick, 1e-4)
    router.demand_tokens_per_replica = float(
        cfg.rows * (prompt_len + cfg.new_tokens))
    monitor = SLOMonitor(tsdb, calibrated_default_slos(
        TTFT_SLO_TICKS, decode_threshold))
    scaler = FleetScaler(
        router, engine_factory,
        ScalerConfig(
            min_replicas=1, max_replicas=cfg.max_replicas,
            scale_up_cooldown_evals=cfg.scale_up_cooldown_evals,
            scale_down_stable_evals=cfg.scale_down_stable_evals,
            idle_to_zero_evals=cfg.idle_to_zero_evals,
            drain_grace_evals=cfg.drain_grace_evals,
            hang_detect_evals=cfg.hang_detect_evals),
        monitor=monitor, tracer=tracer,
        on_release=standby.append,
        # the tentpole wiring: every scaled replica claims its chip
        # from the SAME ledger the batch gangs occupy
        chipsched=ledger,
        chips_per_replica=cfg.serving_chips_per_replica,
        tenant="serving")
    if frozen:
        ledger.freeze()  # the sched_freeze chaos: granting stops

    prompts = make_prompts(
        int(cfg.day_ticks * max(cfg.peak1_rate, cfg.peak2_rate)) + 64,
        seed=cfg.seed, vocab=gpt_cfg.vocab_size,
        prompt_len=cfg.prompt_body, shared_prefix=cfg.shared_prefix)

    handles: dict[int, object] = {}
    retries: list[tuple[int, int]] = []
    shed_retries = 0
    ttft_ticks: list[int] = []
    arrival_tick: dict[int, int] = {}
    first_tok_tick: dict[int, int] = {}
    cur_tick = [0]
    collected: set[int] = set()
    n_submitted = 0
    replicas_peak = 1

    def _note_first_token(idx: int):
        def cb(_freq, _tok):
            first_tok_tick.setdefault(idx, cur_tick[0])
        return cb

    def submit(idx: int, tick: int) -> None:
        nonlocal shed_retries
        try:
            handles[idx] = router.submit(
                prompts[idx], max_new_tokens=cfg.new_tokens,
                on_token=_note_first_token(idx))
            arrival_tick[idx] = tick
        except FleetOverloaded as exc:
            shed_retries += 1
            delay = min(max(1, round(exc.retry_after_s
                                     / max(healthy_tick, 1e-9))), 25)
            retries.append((tick + delay, idx))

    def one_tick(tick: int, arrivals: int) -> None:
        nonlocal n_submitted, replicas_peak
        cur_tick[0] = tick
        for _ in range(arrivals):
            if n_submitted < len(prompts):
                submit(n_submitted, tick)
                n_submitted += 1
        for due, idx in list(retries):
            if due <= tick:
                retries.remove((due, idx))
                submit(idx, tick)
        for rep in list(router.replicas):
            if rep.alive:
                rep.engine.tick()
        for idx, h in list(handles.items()):
            if idx not in collected and h.done.is_set() \
                    and h.error is None:
                collected.add(idx)
                if idx in first_tok_tick:
                    dt = first_tok_tick[idx] - arrival_tick[idx]
                    ttft_ticks.append(dt)
                    tsdb.record(
                        'kftpu_fleet_ttft_seconds{quantile="0.99"}',
                        float(dt))
        tsdb.record("kftpu_fleet_requests_failed_total",
                    router.metrics["requests_failed_total"])
        tsdb.record("kftpu_prof_goodput_ratio", leg.step(tick))
        if tick % cfg.slo_eval_every == 0:
            monitor.evaluate()
        scaler.evaluate()
        # rebind on the tick that freed chips: a drain completed in
        # THIS evaluate released its claim — give the evicted gang its
        # synchronous admission pass now, not at the 0.5s poll
        leg.nudge()
        replicas_peak = max(replicas_peak, len(router._admittable()))

    t0 = time.perf_counter()
    tick = 0
    try:
        for tick in range(cfg.day_ticks):
            one_tick(tick, _arrivals(arrival_rate(tick, cfg), rng))
        # night: serve out the backlog, then keep the loop alive until
        # the evicted gang is back (the scale-down that frees its
        # chips is itself ticks away) — both bounded
        while tick < cfg.day_ticks + cfg.max_drain_ticks:
            tick += 1
            if (not retries
                    and all(h.done.is_set() for h in handles.values())
                    and len(handles) + len(retries) >= n_submitted):
                break
            one_tick(tick, 0)
        resume_deadline = tick + cfg.resume_wait_ticks
        while not frozen and not leg.all_bound() \
                and tick < resume_deadline:
            tick += 1
            one_tick(tick, 0)
    finally:
        wall_s = time.perf_counter() - t0
        batch = leg.finish()
        for rep in router.replicas:
            rep.engine.stop()
        shutil.rmtree(workdir, ignore_errors=True)

    dropped = sum(
        1 for h in handles.values()
        if h.error is not None or not h.done.is_set()
    ) + len(retries)

    report = build_slo_report_from_spans(tracer.snapshot(),
                                         monitor=monitor)
    states = {s["name"]: s for s in report["slos"]}
    serving_alerts = [a["slo"] for a in report["alerts"]
                      if a["slo"].startswith("serving_")]
    worst_burn = 0.0
    for name in ("serving_ttft_p99", "serving_decode_tick",
                 "serving_zero_drop"):
        rates = states.get(name, {}).get("burn_rates", {})
        if rates:
            worst_burn = max(worst_burn, max(rates.values()))

    def _p99(values):
        s = sorted(values)
        return s[min(len(s) - 1, int(len(s) * 0.99))] if s else 0.0

    resume_mean = (sum(batch["resume_ticks"])
                   / len(batch["resume_ticks"])
                   if batch["resume_ticks"] else 0.0)
    m = scaler.metrics
    # conservation audit: a full day of preempt/grant/release churn
    # must leave the ledger internally consistent (asserts inside)
    ledger_audit = ledger.audit()

    return {
        "seed": cfg.seed,
        "frozen": frozen,
        "ticks": tick + 1,
        "day_ticks": cfg.day_ticks,
        "wall_s": round(wall_s, 3),
        "capacity_chips": cfg.capacity_chips,
        "chips_per_slice": cfg.chips_per_slice,
        "n_requests": n_submitted,
        "completed": len(collected),
        "dropped": dropped,
        "shed_retries": shed_retries,
        "requeued": router.metrics["requests_requeued_total"],
        "replicas_peak": replicas_peak,
        "in_day_engine_builds": in_day_builds[0],
        "scaler": dict(m),
        "chip_denies": m["chip_denies_total"],
        "sched": dict(ledger.metrics),
        "sched_snapshot": ledger.snapshot(),
        "ledger_audit": ledger_audit,
        "batch": batch,
        "preempt_to_resume_ticks_mean": round(resume_mean, 2),
        "preempt_to_resume_ticks_max": float(
            max(batch["resume_ticks"], default=0)),
        "preempt_to_resume_s": list(ledger.preempt_to_resume_s),
        "ttft_p99_ticks": float(_p99(ttft_ticks)),
        "ttft_bad_frac": round(
            sum(1 for t in ttft_ticks if t > TTFT_SLO_TICKS)
            / max(len(ttft_ticks), 1), 4),
        "ttft_threshold_ticks": TTFT_SLO_TICKS,
        "healthy_tick_s": round(healthy_tick, 6),
        "slo": {
            "alerts": [a["slo"] for a in report["alerts"]],
            "serving_alerts": serving_alerts,
            "worst_serving_burn": round(worst_burn, 4),
            "states": {
                name: {"fired": st["fired"],
                       "burn_rates": st["burn_rates"],
                       "samples": st["samples"]}
                for name, st in states.items()
            },
        },
        "report": {
            "requests": report["requests"],
            "tsdb": report["tsdb"],
        },
    }


# ------------------------------------------------ the day, on real pods


@dataclass(frozen=True)
class PodSoakConfig:
    """One compact production day on REAL pod subprocesses over the TCP
    transport: every replica is a podworker behind the length-prefixed
    wire, so the day's faults cross actual sockets. The in-process day
    (SoakConfig / run_prod_day) owns the scale-to-zero and SLO-burn
    story; this one owns the NETWORK failure matrix — a SIGKILL
    discovered through the wire, a SIGSTOP indicted by heartbeat age,
    and a mid-peak partition that heals only AFTER the scaler has
    replaced the victim, with the fenced claim's late deliveries
    refused (docs/serving.md "Pod-backed replicas": epoch fencing).
    Sized to run in seconds on CPU behind a shared XLA compile cache."""

    seed: int = 23
    day_ticks: int = 90
    #: diurnal peaks in arrivals/tick (arrival_rate reads these through
    #: the same two-peak profile as the in-process day)
    peak1_rate: float = 0.5
    peak2_rate: float = 0.6
    prompt_body: int = 4
    shared_prefix: int = 2
    new_tokens: int = 4
    block: int = 4
    #: fleet bounds; the floor is 2 so every fault's victim is REPLACED
    #: (the partition-heal gate is "heal after replacement") — the
    #: scale-down/scale-to-zero transitions belong to run_prod_day
    min_replicas: int = 2
    max_replicas: int = 3
    #: pre-spawned warm workers: initial replica + min-floor top-up +
    #: peak headroom + one replacement per injected fault
    standby: int = 6
    #: fault schedule, day fractions: SIGKILL in peak 1, SIGSTOP early
    #: peak 2, partition late peak 2
    kill_at: float = 0.30
    hang_at: float = 0.62
    partition_at: float = 0.74
    #: hang indictment is heartbeat-only here (beats ride the tick
    #: verb, so a SIGSTOPped worker's age grows while its mirrored
    #: step_count freezes — the wall-clock path the in-process day
    #: cannot exercise)
    heartbeat_max_age_s: float = 0.4
    scale_up_cooldown_evals: int = 2
    #: per-op wire timeout: ALSO a failure detector here — a submit the
    #: router routes to the SIGSTOPped pod wedges a round-trip, and
    #: this bound (not the 30s production default) is what converts it
    #: to a pod death when traffic reaches the wedge before the
    #: heartbeat watch does
    op_timeout_s: float = 2.0
    #: drain ticks are wire round-trips (~ms); the bound must cover the
    #: heartbeat ceiling's wall-clock wait
    max_drain_ticks: int = 20000
    transport: str = "tcp"
    #: persistent XLA cache shared across the workers (None = the
    #: stable per-machine temp path; tests pass their repo-local cache)
    compile_cache_dir: str | None = None


def run_prod_day_pods(cfg: PodSoakConfig | None = None) -> dict:
    """The production day re-composed on a spawn_pod fleet (class
    docstring above): diurnal traffic + autoscaler + torn checkpoint,
    with every replica a live subprocess dialed over `cfg.transport`.

    The three faults and what each must prove:

      - **SIGKILL** (peak 1): the client discovers the corpse through
        the wire (reset, redial refused, retries exhausted), the router
        requeues, the scaler replaces — zero drops.
      - **SIGSTOP** (peak 2): sockets stay open, mirrored counters
        freeze. TWO independent detectors race: the heartbeat age
        (ScalerConfig.heartbeat_max_age_s) indicts the wedge if no
        traffic touches it first; a submit the router routes to it
        wedges a round-trip until the op timeout converts it to a pod
        death. Either way the wedged pod ends dead, replaced, with its
        work requeued — the drill gates the outcome, not the winner.
      - **partition** (late peak 2): the victim's host becomes
        unreachable (set_partitioned — nothing crosses, the WORKER
        KEEPS RUNNING). The connection supervisor burns its retry
        budget, the death fences the claim, the router requeues, the
        scaler replaces. Only after the replacement lands does the
        partition HEAL; a fenced_poll then reads the stale worker's
        late deliveries and refuses every one — the zero-duplicate
        proof the drill returns.

    Gates (pinned by tests/test_soak.py): dropped == 0 EXACT,
    token_overruns == 0 (every completed stream is single-copy), and
    partition.healed_after_replacement with the fenced claim refusing
    all late events."""
    import os
    import signal

    from kubeflow_tpu.serving.fleet import (
        PagedKVPool as _Pool,
        spawn_pod,
        wire_pod_deaths,
    )
    from kubeflow_tpu.serving.fleet.podclient import (
        attach_router_death,
        pod_metrics_snapshot,
    )
    from kubeflow_tpu.serving.fleet.wire import PodWireError

    cfg = cfg or PodSoakConfig()
    rng = random.Random(f"kftpu-pods-soak-{cfg.seed}")
    vocab = 64
    prompt_len = cfg.shared_prefix + cfg.prompt_body
    warm = make_prompts(1, seed=cfg.seed + 99, vocab=vocab,
                        prompt_len=cfg.prompt_body,
                        shared_prefix=cfg.shared_prefix)
    spec = {
        "model": {"vocab_size": vocab, "hidden_size": 32, "num_layers": 1,
                  "num_heads": 2, "mlp_dim": 64, "dropout_rate": 0.0,
                  "max_len": prompt_len + cfg.new_tokens + 24},
        "seed": 0, "init_seed": 7, "max_rows": 2,
        "default_max_new_tokens": cfg.new_tokens, "eos_token_id": None,
        "prefill_chunk": 0,
        "pool": {"block_size": cfg.block, "capacity_blocks": 256},
        "warmup_prompts": [[int(t) for t in p] for p in warm],
        "warmup_new_tokens": cfg.new_tokens, "warmup_repeats": 1,
        "warmup_resume": True,
        "max_queue": 64,
        "compile_cache_dir": cfg.compile_cache_dir or os.path.join(
            tempfile.gettempdir(), "kftpu-prof-pods-xla-cache"),
    }
    state_dir = tempfile.mkdtemp(prefix="kftpu-pods-soak-")
    home = _Pool(block_size=cfg.block, capacity_blocks=1024)
    all_pods: list = []

    def _spawn(name: str, connect: bool):
        c = spawn_pod(name, spec, state_dir, home_pool=home,
                      connect=connect, transport=cfg.transport,
                      op_timeout_s=cfg.op_timeout_s)
        all_pods.append(c)
        return c

    t0 = time.perf_counter()
    try:
        # warm the whole pool CONCURRENTLY (the serve_pods trick): total
        # cold start is one worker's warmup, not standby's
        standby = [_spawn(f"pods-{i}", connect=False)
                   for i in range(cfg.standby + 1)]
        for c in standby:
            c.connect()
        in_day_spawns = [0]
        first = standby.pop()
        router = FleetRouter([("pods-base", first)], max_requeues=5)
        wire_pod_deaths(router)

        def engine_factory():
            if standby:
                c = standby.pop()
            else:
                in_day_spawns[0] += 1
                c = _spawn(f"pods-cold-{in_day_spawns[0]}", connect=True)
            attach_router_death(c, router)
            return c

        # admission shedding is last-resort (the run_prod_day
        # reasoning); the demand signal runs on queue math — two seated
        # rows per pod is the working set
        router.ttft_slo_s = 60.0
        router.retry_after_s = 0.01
        router.demand_tokens_per_replica = float(
            2 * (prompt_len + cfg.new_tokens))
        scaler = FleetScaler(
            router, engine_factory,
            ScalerConfig(
                min_replicas=cfg.min_replicas,
                max_replicas=cfg.max_replicas,
                scale_up_cooldown_evals=cfg.scale_up_cooldown_evals,
                scale_down_stable_evals=10 ** 6,  # no drains: this
                # day's transitions are the fault replacements
                hang_detect_evals=10 ** 6,        # heartbeat-only
                heartbeat_max_age_s=cfg.heartbeat_max_age_s))

        prompts = make_prompts(
            int(cfg.day_ticks * max(cfg.peak1_rate, cfg.peak2_rate)) + 16,
            seed=cfg.seed, vocab=vocab, prompt_len=cfg.prompt_body,
            shared_prefix=cfg.shared_prefix)
        kill_tick = int(cfg.kill_at * cfg.day_ticks)
        hang_tick = int(cfg.hang_at * cfg.day_ticks)
        part_tick = int(cfg.partition_at * cfg.day_ticks)

        handles: dict[int, object] = {}
        retries: list[tuple[int, int]] = []
        collected: set[int] = set()
        hung: set[str] = set()
        pod_base = pod_metrics_snapshot()
        n_submitted = 0
        shed_retries = 0
        kills_done = 0
        hang_done = False
        replicas_peak = 1
        ckpt: dict = {}
        hang_victim = None  # the SIGSTOPped pod's PodClient
        pvictim = None      # the partition victim's PodClient
        part = {"injected_tick": None, "victim": None, "death_tick": None,
                "adds_before": 0, "healed_tick": None,
                "healed_after_replacement": False,
                "worker_survived_partition": False,
                "late_events": 0, "late_tokens": 0, "refused": 0}

        def submit(idx: int, tick: int) -> None:
            nonlocal shed_retries
            try:
                handles[idx] = router.submit(
                    prompts[idx], max_new_tokens=cfg.new_tokens)
            except FleetOverloaded:
                shed_retries += 1
                retries.append((tick + 2, idx))

        def one_tick(tick: int, arrivals: int) -> None:
            nonlocal n_submitted, kills_done, hang_done, replicas_peak
            nonlocal pvictim, hang_victim
            # faults first (the drill order: the world breaks, then
            # serves). Victims must hold seated work — an idle victim
            # proves nothing — and enough healthy peers must remain.
            candidates = [r for r in router._admittable()
                          if r.name not in hung]
            if not kills_done and tick >= kill_tick \
                    and len(candidates) >= 2:
                busy = [r for r in candidates if r.depth() > 0]
                if busy:
                    victim = busy[rng.randrange(len(busy))]
                    kills_done += 1
                    os.kill(victim.engine.worker_pid, signal.SIGKILL)
            if not hang_done and tick >= hang_tick \
                    and len(candidates) >= 2:
                busy = [r for r in candidates if r.depth() > 0]
                if busy:
                    hung.add(busy[0].name)
                    hang_victim = busy[0].engine
                    hang_done = True
                    os.kill(hang_victim.worker_pid, signal.SIGSTOP)
            if pvictim is None and tick >= part_tick \
                    and len(candidates) >= 2:
                busy = [r for r in candidates if r.depth() > 0] \
                    or candidates
                rep = busy[rng.randrange(len(busy))]
                pvictim = rep.engine
                part["injected_tick"] = tick
                part["victim"] = rep.name
                part["adds_before"] = \
                    scaler.metrics["replicas_added_total"]
                pvictim.set_partitioned(True)
            # the partition HEALS only after the scaler has landed the
            # replacement — the split-brain window the fence closes
            if pvictim is not None and part["healed_tick"] is None \
                    and pvictim.dead \
                    and scaler.metrics["replicas_added_total"] \
                    > part["adds_before"]:
                if part["death_tick"] is None:
                    part["death_tick"] = tick
                part["worker_survived_partition"] = (
                    pvictim.proc is not None
                    and pvictim.proc.poll() is None)
                pvictim.set_partitioned(False)
                part["healed_tick"] = tick
                part["healed_after_replacement"] = True
            # a dead pod can't stay "hung" — whichever detector won
            # (heartbeat indictment or the submit timeout), the kill
            # ends the SIGSTOP episode. Checked on the CLIENT, not the
            # replica list: the scaler REMOVES indicted replicas.
            if hang_victim is not None and hang_victim.dead:
                hung.clear()
            for _ in range(arrivals):
                if n_submitted < len(prompts):
                    submit(n_submitted, tick)
                    n_submitted += 1
            for due, idx in list(retries):
                if due <= tick:
                    retries.remove((due, idx))
                    submit(idx, tick)
            for rep in list(router.replicas):
                if rep.alive and rep.name not in hung:
                    rep.engine.tick()
            for idx, h in list(handles.items()):
                if idx not in collected and h.done.is_set() \
                        and h.error is None:
                    collected.add(idx)
            scaler.evaluate()
            replicas_peak = max(replicas_peak,
                                len(router._admittable()))

        tick = 0
        for tick in range(cfg.day_ticks):
            if not ckpt and tick >= cfg.day_ticks // 2:
                ckpt = _torn_checkpoint()  # the mid-day torn save
            one_tick(tick, _arrivals(arrival_rate(tick, cfg), rng))
        # night drain: no arrivals; the backlog AND the in-flight fault
        # episodes (a pending heartbeat indictment, the partition heal)
        # must all settle — drain ticks are real wire round-trips, so
        # the heartbeat ceiling's wall-clock wait passes through here
        while tick < cfg.day_ticks + cfg.max_drain_ticks:
            tick += 1
            served = (not retries
                      and all(h.done.is_set() for h in handles.values())
                      and len(handles) + len(retries) >= n_submitted)
            settled = (not hung
                       and (pvictim is None
                            or part["healed_tick"] is not None))
            if served and settled:
                break
            one_tick(tick, 0)

        # ---- the heal probe: the fenced claim's worker is reachable
        # again — whatever its outbox still holds (events delivered but
        # never acked, plus one tick of fresh decode on rows the fleet
        # already re-served elsewhere) must be REFUSED, not applied
        if pvictim is not None and pvictim.fenced \
                and not pvictim.partitioned \
                and pvictim.proc is not None \
                and pvictim.proc.poll() is None:
            try:
                probe = pvictim.fenced_poll(timeout_s=5.0)
                part["late_events"] = probe["late_events"]
                part["late_tokens"] = probe["late_tokens"]
                part["refused"] = probe["refused"]
            except (PodWireError, RuntimeError, OSError) as e:
                part["probe_error"] = str(e)

        dropped = sum(
            1 for h in handles.values()
            if h.error is not None or not h.done.is_set()
        ) + len(retries)
        # single-copy proof: every completed stream carries EXACTLY the
        # requested tokens — a duplicate delivery that slipped the
        # ack/fence filters would overrun
        token_overruns = 0
        for idx in collected:
            if len(handles[idx].result(timeout=5.0)) != cfg.new_tokens:
                token_overruns += 1
        pod_now = pod_metrics_snapshot()
        m = scaler.metrics
        return {
            "seed": cfg.seed,
            "transport": cfg.transport,
            "ticks": tick + 1,
            "day_ticks": cfg.day_ticks,
            "wall_s": round(time.perf_counter() - t0, 3),
            "n_requests": n_submitted,
            "completed": len(collected),
            "dropped": dropped,
            "shed_retries": shed_retries,
            "token_overruns": token_overruns,
            "requeued": router.metrics["requests_requeued_total"],
            "resumed": router.metrics["requeues_resumed_total"],
            "kills_injected": kills_done,
            "hang_injected": hang_done,
            "hang_victim_dead": (hang_victim is not None
                                 and hang_victim.dead),
            "hangs_indicted": m["hangs_detected_total"],
            "partition": dict(part),
            "replicas_peak": replicas_peak,
            "in_day_spawns": in_day_spawns[0],
            "standby_left": len(standby),
            "ckpt": ckpt,
            "scaler": dict(m),
            "pod_metrics": {
                k: pod_now[k] - pod_base[k]
                for k in ("net_reconnects_total",
                          "net_partitions_injected_total",
                          "net_fenced_frames_total",
                          "net_duplicate_acks_refused_total",
                          "wire_retries_total",
                          "wire_retries_exhausted_total",
                          "kills_total")
            },
        }
    finally:
        # drill teardown, not the production path: partitioned and
        # disowned deaths deliberately leave their workers running
        # (that IS the split-brain hazard) — reap every survivor here
        for c in all_pods:
            try:
                c.stop()
            except RuntimeError:  # teardown best-effort
                pass
            c.partitioned = False
            c._disowned = False
            c._kill_process()
        shutil.rmtree(state_dir, ignore_errors=True)
