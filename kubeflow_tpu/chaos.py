"""Deterministic chaos layer: seeded fault injection for recovery drills.

The platform's core contract is gang-restart-from-checkpoint fault tolerance
(SURVEY.md §5.3-§5.4); this module is how that contract gets *exercised*.
A FaultPlan is a seed-derived, byte-for-byte reproducible schedule of faults;
a ChaosEngine attached to a Platform injects them at the layer boundaries the
real system fails at:

  - FakeCluster.update        -> ConflictError storms (apiserver 409 bursts)
  - WatchSubscription.get     -> dropped watch streams (forced relists, the
                                 'resourceVersion expired' path) and delayed
                                 event delivery (informer lag)
  - PodRuntime._launch        -> startup stalls (slow image pull / TPU slice
                                 allocation)
  - running pods              -> kills with retryable (signal -> 128+signum)
                                 or non-retryable exit codes, and HANGS
                                 (SIGSTOP: the process stays alive, exits
                                 never, heartbeats stop — the liveness
                                 layer's lease detector is the only thing
                                 that can catch it, docs/health.md)
  - heartbeat writes          -> dropped liveness reports (a healthy worker
                                 that LOOKS hung), armed in-process via
                                 HeartbeatWriter.chaos or cross-process via
                                 the KFTPU_HB_DROP env carrier
  - pod wire calls            -> connection resets, replies delayed past
                                 the propagated deadline, torn/truncated
                                 frames (the podclient transport — faults
                                 no in-process kill can reach)
  - Checkpointer saves        -> fsync delays and torn writes (an atomic-
                                 rename checkpointer surfaces a torn write as
                                 a MISSING newest checkpoint, so injection
                                 drops the save after the delay)
  - Checkpointer restores     -> restore-side corruption: the newest
                                 COMMITTED step's bytes are flipped before
                                 the restore, exercising the verify ->
                                 quarantine -> fallback path

Reproducibility contract: FaultPlan.from_seed(s) is a pure function of
(s, profile) — plan.describe() is byte-identical across runs and
plan.digest() names it. Injection *order* under free-running threads is not
replayed tick-for-tick (neither are real outages); the drill suite instead
asserts semantic convergence — every drill ends Succeeded/Ready within a
bounded reconcile budget. To reproduce a failed drill, re-run with its
logged seed: the same faults are armed with the same parameters.
"""

from __future__ import annotations

import fnmatch
import hashlib
import random
import signal
import threading
import time
from dataclasses import dataclass, field, fields

from kubeflow_tpu.analysis.lockcheck import make_lock
from kubeflow_tpu.controller.fakecluster import ConflictError, PodPhase
from kubeflow_tpu.utils.retry import with_conflict_retry


# --------------------------------------------------------------- fault specs


@dataclass(frozen=True)
class ConflictStorm:
    """Reject a fraction of updates on one kind with ConflictError until the
    injection budget is spent (apiserver optimistic-concurrency burst)."""

    kind: str = "jobs"
    rate: float = 0.5
    count: int = 8


@dataclass(frozen=True)
class WatchDrop:
    """Force a full relist on every Nth watch delivery (the 'watch too old'
    recovery path), `count` times total across all subscriptions."""

    every_n: int = 40
    count: int = 4


@dataclass(frozen=True)
class EventDelay:
    """Stall a fraction of watch deliveries by delay_s (informer lag)."""

    rate: float = 0.15
    delay_s: float = 0.03
    count: int = 40


@dataclass(frozen=True)
class PodKill:
    """Kill up to `times` distinct running pods matching `name_glob` after
    they have been running for `after_running_s`. signal != 0 kills the real
    process (exit normalizes to 128+signum — retryable); signal == 0 instead
    marks the pod Failed with `exit_code` (non-retryable codes < 128)."""

    name_glob: str = "*"
    after_running_s: float = 0.2
    signal: int = int(signal.SIGKILL)
    exit_code: int = 1
    times: int = 1


@dataclass(frozen=True)
class StartStall:
    """Delay the launch of up to `count` pods matching `name_glob` by
    delay_s (slow image pull / TPU slice allocation)."""

    name_glob: str = "*"
    delay_s: float = 0.25
    count: int = 1


@dataclass(frozen=True)
class PodHang:
    """SIGSTOP up to `times` distinct running pods matching `name_glob`
    after they have run for `after_running_s`: the process stays ALIVE (no
    exit code ever), its heartbeats stop — the deadlocked-collective /
    stuck-data-loader failure mode only lease expiry can detect."""

    name_glob: str = "*"
    after_running_s: float = 0.2
    times: int = 1


@dataclass(frozen=True)
class HeartbeatDrop:
    """Drop a fraction of heartbeat writes, `count` total — liveness
    reports lost in transit, so detection tuning gets exercised against
    flaky reporting, not just clean silence. In-process writers consult the
    engine directly; subprocess workers get the same schedule via the
    KFTPU_HB_DROP env carrier ("rate:seed:count") injected at pod launch."""

    rate: float = 0.3
    count: int = 10


@dataclass(frozen=True)
class WireFault:
    """Fault one pod-wire client call (serving/fleet/podclient.py):
    kind='reset' closes the socket before the request goes out
    (connection reset mid-stream -> redial + retry), kind='delay' stalls
    the call by delay_s so a propagated Deadline expires in flight, and
    kind='torn' truncates the reply frame mid-read (the length prefix
    makes the tear detectable — PodWireError, never a resync). Each
    matching call draws at `rate` until `count` injections are spent."""

    kind: str = "reset"
    rate: float = 0.5
    delay_s: float = 0.0
    count: int = 2


@dataclass(frozen=True)
class NetFault:
    """The TCP failure family on the pod wire — faults AF_UNIX can
    never produce (serving/fleet/podclient.py). kind='blackhole' eats
    one outbound frame before delivery (the replay after reconnect is a
    FIRST delivery); kind='halfopen' delivers the frame but loses the
    reply (the worker processed it — the retry's replay is a DUPLICATE
    only rid-dedup and cumulative acks keep exact); kind='dup' loses an
    ack in flight so the worker redelivers already-applied events (the
    client's id-filter must refuse every copy); kind='partition' opens
    a stateful window of `ops` consecutive calls during which every
    frame is lost in both directions. Each matching call draws at
    `rate` until `count` injections (windows, for partition) spend."""

    kind: str = "blackhole"
    rate: float = 0.5
    ops: int = 3
    count: int = 1


@dataclass(frozen=True)
class CheckpointFault:
    """save() faults: every save sleeps save_delay_s (slow fsync); every
    torn_every_n-th save is dropped after the delay (torn write under
    atomic-rename semantics = the checkpoint never becomes visible).
    restore faults: every corrupt_restore_every_n-th restore_latest first
    flips bytes in the newest committed step, so the verify-on-restore ->
    quarantine -> fallback contract is what gets drilled."""

    save_delay_s: float = 0.02
    torn_every_n: int = 0
    corrupt_restore_every_n: int = 0


@dataclass(frozen=True)
class FaultPlan:
    """A complete, seed-stamped fault schedule. Immutable; describe() is the
    canonical byte-stable form and digest() its reproducibility fingerprint."""

    seed: int
    conflict_storms: tuple[ConflictStorm, ...] = ()
    watch_drops: tuple[WatchDrop, ...] = ()
    event_delays: tuple[EventDelay, ...] = ()
    pod_kills: tuple[PodKill, ...] = ()
    start_stalls: tuple[StartStall, ...] = ()
    pod_hangs: tuple[PodHang, ...] = ()
    heartbeat_drops: tuple[HeartbeatDrop, ...] = ()
    wire_faults: tuple[WireFault, ...] = ()
    net_faults: tuple[NetFault, ...] = ()
    checkpoint: CheckpointFault | None = None

    @classmethod
    def from_seed(cls, seed: int, profile: str = "default") -> "FaultPlan":
        """Derive a plan from a seed — same (seed, profile) => identical
        plan, byte for byte. Profiles pick which layers get hit:

          default   — a bit of everything, drill-sized
          apiserver — conflict storms + watch drops only
          pods      — kills + startup stalls only
          storage   — checkpoint faults only
          liveness  — hangs, heartbeat drops, restore-side corruption (the
                      failure modes only the health layer can catch)
          wire      — pod-wire faults (reset / delay / torn frame on the
                      podclient transport) joined with the TCP net
                      family (black hole / half-open / duplicate
                      delivery / partition)
          net       — the TCP net family alone (the serve_pods_tcp
                      gate's teeth: every fault here is one AF_UNIX
                      cannot produce)
        """
        rng = random.Random(f"kftpu-chaos-{profile}-{seed}")
        r = lambda lo, hi: round(rng.uniform(lo, hi), 4)  # noqa: E731
        apiserver = profile in ("default", "apiserver")
        pods = profile in ("default", "pods")
        storage = profile in ("default", "storage")
        liveness = profile == "liveness"
        if profile not in ("default", "apiserver", "pods", "storage",
                           "liveness", "wire", "net"):
            raise ValueError(f"unknown chaos profile {profile!r}")

        def net_draw() -> tuple[NetFault, ...]:
            return (
                NetFault("blackhole", rate=r(0.3, 0.7),
                         count=rng.randint(1, 2)),
                NetFault("halfopen", rate=r(0.2, 0.5),
                         count=rng.randint(1, 2)),
                NetFault("dup", rate=r(0.2, 0.5),
                         count=rng.randint(1, 2)),
                NetFault("partition", rate=r(0.1, 0.3),
                         ops=rng.randint(2, 4), count=1),
            )

        if profile == "net":
            return cls(seed=seed, net_faults=net_draw())
        if profile == "wire":
            # draw order is part of the plan contract: the PR-15 wire
            # faults draw FIRST (identical to the pre-net plans for a
            # given seed), the net family extends the same stream after
            return cls(
                seed=seed,
                wire_faults=(
                    WireFault("reset", rate=r(0.3, 0.7),
                              count=rng.randint(1, 3)),
                    WireFault("delay", rate=r(0.2, 0.5),
                              delay_s=r(0.05, 0.2),
                              count=rng.randint(1, 2)),
                    WireFault("torn", rate=r(0.3, 0.7),
                              count=rng.randint(1, 3)),
                ),
                net_faults=net_draw(),
            )
        if liveness:
            return cls(
                seed=seed,
                pod_hangs=(
                    PodHang("*", after_running_s=r(0.1, 0.5), times=1),
                ),
                heartbeat_drops=(
                    HeartbeatDrop(rate=r(0.2, 0.5),
                                  count=rng.randint(5, 15)),
                ),
                checkpoint=CheckpointFault(
                    save_delay_s=0.0, torn_every_n=0,
                    corrupt_restore_every_n=rng.randint(2, 4),
                ),
            )
        return cls(
            seed=seed,
            conflict_storms=(
                ConflictStorm("jobs", rate=r(0.2, 0.6), count=rng.randint(4, 10)),
                ConflictStorm("pods", rate=r(0.1, 0.4), count=rng.randint(4, 10)),
            ) if apiserver else (),
            watch_drops=(
                WatchDrop(every_n=rng.randint(30, 80), count=rng.randint(2, 5)),
            ) if apiserver else (),
            event_delays=(
                EventDelay(rate=r(0.05, 0.2), delay_s=r(0.01, 0.05),
                           count=rng.randint(20, 60)),
            ) if apiserver else (),
            pod_kills=(
                PodKill("*", after_running_s=r(0.1, 0.5), times=1),
            ) if pods else (),
            start_stalls=(
                StartStall("*", delay_s=r(0.1, 0.4), count=rng.randint(1, 2)),
            ) if pods else (),
            checkpoint=CheckpointFault(
                save_delay_s=r(0.005, 0.05), torn_every_n=rng.randint(2, 4)
            ) if storage else None,
        )

    def describe(self) -> str:
        """Canonical text form — field order fixed by the dataclass
        definitions, floats already rounded at construction, no dict
        iteration anywhere: byte-for-byte stable for a given plan."""
        lines = [f"fault-plan seed={self.seed}"]

        def emit(label: str, spec) -> None:
            kv = " ".join(
                f"{f.name}={getattr(spec, f.name)!r}" for f in fields(spec)
            )
            lines.append(f"  {label}: {kv}")

        for s in self.conflict_storms:
            emit("conflict-storm", s)
        for s in self.watch_drops:
            emit("watch-drop", s)
        for s in self.event_delays:
            emit("event-delay", s)
        for s in self.pod_kills:
            emit("pod-kill", s)
        for s in self.start_stalls:
            emit("start-stall", s)
        for s in self.pod_hangs:
            emit("pod-hang", s)
        for s in self.heartbeat_drops:
            emit("heartbeat-drop", s)
        for s in self.wire_faults:
            emit("wire-fault", s)
        for s in self.net_faults:
            emit("net-fault", s)
        if self.checkpoint is not None:
            emit("checkpoint", self.checkpoint)
        return "\n".join(lines) + "\n"

    def digest(self) -> str:
        return hashlib.sha256(self.describe().encode()).hexdigest()[:16]


# -------------------------------------------------------------------- engine


@dataclass
class _KillState:
    """Budget tracker for a pod-targeting fault (kills and hangs share the
    spec shape: name_glob / after_running_s / times)."""

    spec: PodKill | PodHang
    remaining: int = field(default=0)

    def __post_init__(self):
        self.remaining = self.spec.times


class ChaosEngine:
    """Arms a FaultPlan against a Platform (or bare cluster/runtime).

    Hook-based, not monkeypatch-based: FakeCluster and PodRuntime carry a
    `chaos` attachment point and call into the engine at their fault
    boundaries; detach() disarms everything. All draws come from one seeded
    RNG under a lock, and every injection increments a counter in
    `self.metrics` (exported as kftpu_chaos_* via observability.py).
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self._mu = make_lock("chaos.ChaosEngine._mu")
        self.metrics: dict[str, int] = {
            "conflicts_injected_total": 0,
            "watch_drops_total": 0,
            "event_delays_total": 0,
            "pod_kills_total": 0,
            "pod_hangs_total": 0,
            "pod_failures_injected_total": 0,
            "pod_failures_lost_races_total": 0,
            "start_stalls_total": 0,
            "hb_drops_total": 0,
            "wire_resets_total": 0,
            "wire_delays_total": 0,
            "wire_torn_total": 0,
            "net_blackholes_total": 0,
            "net_halfopens_total": 0,
            "net_dups_total": 0,
            "net_partitions_total": 0,
            "ckpt_saves_delayed_total": 0,
            "ckpt_saves_torn_total": 0,
            "ckpt_restores_corrupted_total": 0,
        }
        self._storm_budget = {id(s): s.count for s in plan.conflict_storms}
        self._drop_budget = {id(d): d.count for d in plan.watch_drops}
        self._delay_budget = {id(d): d.count for d in plan.event_delays}
        self._stall_budget = {id(s): s.count for s in plan.start_stalls}
        self._hb_budget = {id(h): h.count for h in plan.heartbeat_drops}
        self._wire_budget = {id(w): w.count for w in plan.wire_faults}
        self._net_budget = {id(n): n.count for n in plan.net_faults}
        self._partition_ops_left = 0
        self._kills = [_KillState(k) for k in plan.pod_kills]
        self._hangs = [_KillState(h) for h in plan.pod_hangs]
        self._watch_counts: dict[int, int] = {}
        self._killed_uids: set[str] = set()
        self._ckpt_saves = 0
        self._ckpt_restores = 0
        self._platform = None
        self._cluster = None
        self._runtime = None
        self._stop = threading.Event()
        self._killer: threading.Thread | None = None

    def _tracer(self):
        """The attached cluster's tracer (None when tracing is off): every
        injection lands as an annotated span/event in the SAME timeline the
        recovery unfolds in, so fault cause and recovery cost co-render."""
        return self._cluster.tracer if self._cluster is not None else None

    # ----------------------------------------------------------- lifecycle

    def attach(self, platform=None, cluster=None, pod_runtime=None) -> "ChaosEngine":
        """Arm the plan. Pass a Platform (wires everything + /metrics), or a
        bare cluster and/or pod_runtime for unit-scope drills."""
        self._platform = platform
        self._cluster = cluster if cluster is not None else (
            platform.cluster if platform is not None else None
        )
        self._runtime = pod_runtime if pod_runtime is not None else (
            getattr(platform, "pod_runtime", None)
        )
        if self._cluster is not None:
            self._cluster.chaos = self
        if self._runtime is not None:
            self._runtime.chaos = self
        if platform is not None:
            platform.chaos = self
        if ((self._kills or self._hangs)
                and self._cluster is not None and self._runtime is not None):
            self._killer = threading.Thread(
                target=self._kill_loop, name="chaos-killer", daemon=True
            )
            self._killer.start()
        return self

    def detach(self) -> None:
        self._stop.set()
        if self._killer is not None:
            self._killer.join(timeout=5.0)
            self._killer = None
        if self._cluster is not None and self._cluster.chaos is self:
            self._cluster.chaos = None
        if self._runtime is not None and getattr(self._runtime, "chaos", None) is self:
            self._runtime.chaos = None
        if self._platform is not None and getattr(self._platform, "chaos", None) is self:
            self._platform.chaos = None

    def __enter__(self) -> "ChaosEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.detach()

    def quiescent(self) -> bool:
        """True once every BUDGETED fault is spent (storms, drops, delays,
        kills, hangs, stalls) — asserting convergence only makes sense
        after the armed faults have fully landed. Checkpoint faults are
        periodic (torn_every_n / corrupt_restore_every_n) and heartbeat
        drops may land inside worker processes (the env carrier) where the
        engine cannot observe them — neither blocks quiescence."""
        with self._mu:
            return (
                all(v <= 0 for v in self._storm_budget.values())
                and all(v <= 0 for v in self._drop_budget.values())
                and all(v <= 0 for v in self._delay_budget.values())
                and all(v <= 0 for v in self._stall_budget.values())
                and all(k.remaining <= 0 for k in self._kills)
                and all(h.remaining <= 0 for h in self._hangs)
            )

    # ------------------------------------------------- fakecluster hooks

    def on_update(self, kind: str, key: str) -> None:
        """Called by FakeCluster.update before applying a write; raising
        ConflictError here is indistinguishable from a real stale write, so
        every caller's retry discipline gets exercised for free."""
        with self._mu:
            for storm in self.plan.conflict_storms:
                if storm.kind != kind:
                    continue
                if self._storm_budget.get(id(storm), 0) <= 0:
                    continue
                if self.rng.random() >= storm.rate:
                    continue
                self._storm_budget[id(storm)] -= 1
                self.metrics["conflicts_injected_total"] += 1
                tracer = self._tracer()
                if tracer is not None:
                    # inherits the writer's current span (e.g. the reconcile
                    # pass whose update this 409 is about to reject)
                    tracer.event("chaos.conflict", kind=kind, key=key,
                                 seed=self.plan.seed)
                raise ConflictError(
                    f"chaos[seed={self.plan.seed}]: injected conflict on "
                    f"{kind} {key}"
                )

    def on_watch_get(self, sub_id: int) -> float | str | None:
        """Called once per WatchSubscription delivery attempt. Returns
        'drop' (force a relist), a delay in seconds, or None."""
        with self._mu:
            n = self._watch_counts[sub_id] = self._watch_counts.get(sub_id, 0) + 1
            for d in self.plan.watch_drops:
                if self._drop_budget.get(id(d), 0) > 0 and n % d.every_n == 0:
                    self._drop_budget[id(d)] -= 1
                    self.metrics["watch_drops_total"] += 1
                    tracer = self._tracer()
                    if tracer is not None:
                        tracer.event("chaos.watch_drop", parent=None,
                                     sub=sub_id, seed=self.plan.seed)
                    return "drop"
            for d in self.plan.event_delays:
                if (
                    self._delay_budget.get(id(d), 0) > 0
                    and self.rng.random() < d.rate
                ):
                    self._delay_budget[id(d)] -= 1
                    self.metrics["event_delays_total"] += 1
                    return d.delay_s
        return None

    # -------------------------------------------------- podruntime hooks

    def on_pod_launch(self, pod) -> None:
        """Called by PodRuntime._launch before spawning; sleeping here IS the
        fault (slow image pull / slice allocation stalls the kubelet path)."""
        delay = None
        with self._mu:
            for s in self.plan.start_stalls:
                if self._stall_budget.get(id(s), 0) <= 0:
                    continue
                if not fnmatch.fnmatch(pod.metadata.name, s.name_glob):
                    continue
                self._stall_budget[id(s)] -= 1
                self.metrics["start_stalls_total"] += 1
                delay = s.delay_s
                break
        if delay is not None:
            tracer = self._tracer()
            if tracer is not None:
                # inherits the pod.launch span: the stall shows inside it
                tracer.event("chaos.start_stall", pod=pod.metadata.name,
                             delay_s=delay, seed=self.plan.seed)
            time.sleep(delay)

    def _kill_loop(self) -> None:
        """Watch running pods; kill or hang matching ones per plan. Faults
        are keyed by pod UID, so a restarted incarnation (same name, new
        uid) is a fresh target only while a spec still has budget."""
        due: dict[tuple[str, int], float] = {}
        while not self._stop.is_set():
            with self._mu:
                armed = [k for k in self._kills + self._hangs
                         if k.remaining > 0]
            if not armed:
                return
            now = time.time()  # PodStatus.start_time is wall-clock
            for pod in self._cluster.list("pods"):
                if pod.status.phase != PodPhase.RUNNING:
                    continue
                uid = pod.metadata.uid
                if uid in self._killed_uids:
                    continue
                ks = next(
                    (
                        k for k in armed
                        if fnmatch.fnmatch(pod.metadata.name, k.spec.name_glob)
                    ),
                    None,
                )
                if ks is None:
                    continue
                started = pod.status.start_time or now
                fire_at = due.setdefault(
                    (uid, id(ks)), started + ks.spec.after_running_s
                )
                if now < fire_at:
                    continue
                with self._mu:
                    if ks.remaining <= 0 or uid in self._killed_uids:
                        continue
                    # reserve the budget; restored below if the fault misses
                    ks.remaining -= 1
                    self._killed_uids.add(uid)
                fire = (self._fire_hang if isinstance(ks.spec, PodHang)
                        else self._fire_kill)
                if not fire(pod, ks.spec):
                    # target vanished between snapshot and injection (e.g.
                    # the pod finished): the budget was NOT spent — the next
                    # matching running pod is still a target
                    with self._mu:
                        ks.remaining += 1
                        self._killed_uids.discard(uid)
            self._stop.wait(0.03)

    def _fire_hang(self, pod, spec: PodHang) -> bool:
        """SIGSTOP the pod's process group: alive, unreapable, silent. The
        ONLY recovery path is the liveness lease — exit-code detection
        never fires because there is no exit."""
        tracer = self._tracer()
        if tracer is None:
            return self._fire_hang_inner(pod, spec)
        # a root span: the hang starts the causal chain the lease detector
        # will continue (pod_hang -> missed heartbeats -> lease expiry ->
        # gang restart)
        with tracer.span("chaos.pod_hang", parent=None, pod=pod.key,
                         uid=pod.metadata.uid, seed=self.plan.seed) as sp:
            landed = self._fire_hang_inner(pod, spec)
            sp.set_attribute("landed", landed)
            return landed

    def _fire_hang_inner(self, pod, spec: PodHang) -> bool:
        if not self._runtime.inject_kill(pod.key, signal.SIGSTOP):
            return False
        with self._mu:
            self.metrics["pod_hangs_total"] += 1
        return True

    def _fire_kill(self, pod, spec: PodKill) -> bool:
        """Returns True only when the fault actually landed."""
        tracer = self._tracer()
        if tracer is None:
            return self._fire_kill_inner(pod, spec)
        # a root span: the kill STARTS a causal chain (kill -> pod.exit ->
        # watch -> reconcile -> rebind ...); inject_kill records this
        # context so the runtime's reap parent-links the exit to it
        with tracer.span("chaos.pod_kill", parent=None, pod=pod.key,
                         uid=pod.metadata.uid, signal=spec.signal,
                         exit_code=spec.exit_code,
                         seed=self.plan.seed) as sp:
            landed = self._fire_kill_inner(pod, spec)
            sp.set_attribute("landed", landed)
            return landed

    def _fire_kill_inner(self, pod, spec: PodKill) -> bool:
        if spec.signal:
            if self._runtime.inject_kill(pod.key, spec.signal):
                with self._mu:
                    self.metrics["pod_kills_total"] += 1
                return True
            return False
        # signal == 0: fail the pod via the store with a chosen exit code
        # (non-retryable codes < 128 are unreachable through real signals)
        uid, code = pod.metadata.uid, spec.exit_code

        def attempt():
            cur = self._cluster.get("pods", pod.key, copy_obj=True)
            if cur is None or cur.metadata.uid != uid:
                return None
            cur.status.phase = PodPhase.FAILED
            cur.status.exit_code = code
            cur.status.finish_time = time.time()
            cur.status.message = f"chaos[seed={self.plan.seed}]: injected failure"
            if self._tracer() is not None:
                from kubeflow_tpu.tracing import (
                    CARRIER_ANNOTATION,
                    current_context,
                )

                ctx = current_context()  # the chaos.pod_kill span
                if ctx is not None:
                    cur.metadata.annotations[CARRIER_ANNOTATION] = \
                        ctx.to_header()
            return self._cluster.update("pods", cur)

        try:
            landed = with_conflict_retry(attempt) is not None
        except (ConflictError, KeyError):
            landed = False
        if landed:
            self._runtime.inject_kill(pod.key)  # reap the real process
            with self._mu:
                self.metrics["pod_failures_injected_total"] += 1
            return True
        # pod churned away mid-injection (uid replaced -> attempt returned
        # None, or the write kept conflicting/vanished): the drill moves
        # on — but the lost injection is counted so a plan that *planned*
        # N kills and landed M is a visible difference
        with self._mu:
            self.metrics["pod_failures_lost_races_total"] += 1
        return False

    # ------------------------------------------------- heartbeat hooks

    def on_heartbeat_write(self) -> bool:
        """Called by an in-process HeartbeatWriter with `.chaos` attached;
        True means this liveness report is lost in transit."""
        with self._mu:
            for h in self.plan.heartbeat_drops:
                if self._hb_budget.get(id(h), 0) <= 0:
                    continue
                if self.rng.random() >= h.rate:
                    continue
                self._hb_budget[id(h)] -= 1
                self.metrics["hb_drops_total"] += 1
                return True
        return False

    # ------------------------------------------------- pod-wire hooks

    def on_wire_op(self) -> "str | tuple[str, float] | None":
        """Called by PodClient once per wire call. Returns None (clean),
        'reset' (close the socket before sending), 'torn' (truncate the
        reply mid-read), ('delay', seconds) — stall the call so a
        propagated deadline can expire in flight — or one of the TCP
        net family: 'blackhole' / 'partition' (frame lost before
        delivery; a partition repeats for its whole ops window),
        'halfopen' (frame delivered, reply lost — the retry's replay is
        a duplicate), 'dup' (ack lost in flight — the worker redelivers
        applied events). Like env-carried heartbeat drops, wire and net
        budgets never gate quiescent(): the retry layer absorbs them
        asynchronously and drills assert on the injection counters
        instead."""
        partition_started = False
        fault: "str | tuple[str, float] | None" = None
        with self._mu:
            if self._partition_ops_left > 0:
                self._partition_ops_left -= 1
                return "partition"
            for w in self.plan.wire_faults:
                if self._wire_budget.get(id(w), 0) <= 0:
                    continue
                if self.rng.random() >= w.rate:
                    continue
                self._wire_budget[id(w)] -= 1
                if w.kind == "reset":
                    self.metrics["wire_resets_total"] += 1
                    return "reset"
                if w.kind == "torn":
                    self.metrics["wire_torn_total"] += 1
                    return "torn"
                self.metrics["wire_delays_total"] += 1
                return ("delay", w.delay_s)
            for nf in self.plan.net_faults:
                if self._net_budget.get(id(nf), 0) <= 0:
                    continue
                if self.rng.random() >= nf.rate:
                    continue
                self._net_budget[id(nf)] -= 1
                if nf.kind == "partition":
                    self.metrics["net_partitions_total"] += 1
                    self._partition_ops_left = max(int(nf.ops) - 1, 0)
                    partition_started = True
                    fault = "partition"
                elif nf.kind == "blackhole":
                    self.metrics["net_blackholes_total"] += 1
                    fault = "blackhole"
                elif nf.kind == "halfopen":
                    self.metrics["net_halfopens_total"] += 1
                    fault = "halfopen"
                else:
                    self.metrics["net_dups_total"] += 1
                    fault = "dup"
                break
        if partition_started:
            # mirror into the kftpu_pod_net_* family (outside _mu: the
            # pod-metrics lock is a leaf shared with the wire path).
            # Lazy import — chaos.py must stay importable without the
            # serving tier.
            from kubeflow_tpu.serving.fleet.podclient import (
                pod_metric_bump,
            )

            pod_metric_bump("net_partitions_injected_total")
        return fault

    def pod_env(self, pod) -> dict[str, str]:
        """Extra env for a pod about to launch (PodRuntime._launch_pod):
        heartbeat-drop faults cross the process boundary as the
        KFTPU_HB_DROP carrier, seeded per plan so subprocess workers drop
        the same schedule every run. The FIRST drop spec rides the env and
        its `count` is a PER-WORKER budget, enforced (and counted, via
        HeartbeatWriter.dropped) inside each worker — the engine cannot
        observe out-of-process drops, so they debit no engine budget and
        never gate quiescent()."""
        from kubeflow_tpu.health import ENV_HEARTBEAT_DROP

        for h in self.plan.heartbeat_drops:
            return {
                ENV_HEARTBEAT_DROP: f"{h.rate}:{self.plan.seed}:{h.count}"
            }
        return {}

    # ------------------------------------------------- checkpointer hook

    def on_checkpoint_save(self) -> bool:
        """Returns True when this save should be TORN (dropped after the
        delay); always applies the plan's fsync delay first."""
        ck = self.plan.checkpoint
        if ck is None:
            return False
        with self._mu:
            self._ckpt_saves += 1
            n = self._ckpt_saves
            self.metrics["ckpt_saves_delayed_total"] += 1
            torn = bool(ck.torn_every_n) and n % ck.torn_every_n == 0
            if torn:
                self.metrics["ckpt_saves_torn_total"] += 1
        if ck.save_delay_s > 0:
            time.sleep(ck.save_delay_s)
        return torn

    def on_checkpoint_restore(self) -> bool:
        """Returns True when this restore should find its newest committed
        step CORRUPTED (bytes flipped post-commit — the bit-rot / partial-
        overwrite class orbax's atomic rename cannot protect against). The
        metric is NOT bumped here: an empty dir has nothing to corrupt, so
        the injector reports back via note_ckpt_corruption_landed only once
        bytes actually flipped."""
        ck = self.plan.checkpoint
        if ck is None or not ck.corrupt_restore_every_n:
            return False
        with self._mu:
            self._ckpt_restores += 1
            return self._ckpt_restores % ck.corrupt_restore_every_n == 0

    def note_ckpt_corruption_landed(self) -> None:
        with self._mu:
            self.metrics["ckpt_restores_corrupted_total"] += 1


def corrupt_newest_checkpoint(directory: str) -> int | None:
    """Flip the leading bytes of the newest committed step's largest
    payload file (the manifest itself is left intact — the point is a
    checksum MISMATCH, not a missing manifest). Returns the corrupted step,
    or None when there is nothing committed to corrupt. Shared by the
    restore-fault injection and drills that stage corruption directly."""
    import os

    from kubeflow_tpu.health import CKPT_MANIFEST_NAME

    try:
        steps = [int(n) for n in os.listdir(directory)
                 if n.isdigit() and os.path.isdir(os.path.join(directory, n))]
    except OSError:
        return None
    if not steps:
        return None
    step = max(steps)
    root = os.path.join(directory, str(step))
    candidates = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            if name == CKPT_MANIFEST_NAME or name.endswith(".tmp"):
                continue
            path = os.path.join(dirpath, name)
            try:
                candidates.append((os.path.getsize(path), path))
            except OSError:
                continue
    if not candidates:
        return None
    _size, target = max(candidates)
    with open(target, "r+b") as fh:
        head = fh.read(64)
        fh.seek(0)
        fh.write(bytes(b ^ 0xFF for b in head))
    return step


class ChaosCheckpointer:
    """Fault-injecting wrapper with the Checkpointer save/restore surface.

    Slow saves sleep before committing; torn saves never commit — under
    atomic-rename checkpointing a partial write is exactly a checkpoint
    that fails to become visible, so restore_latest() serves the previous
    step and the resume path gets exercised against real data loss. Armed
    restore corruption flips bytes in the newest COMMITTED step before the
    restore, so the verifying checkpointer's quarantine + fallback path is
    what actually runs.
    """

    def __init__(self, inner, engine: ChaosEngine):
        self._inner = inner
        self._engine = engine

    def save(self, step: int, state, metrics: dict | None = None) -> None:
        if self._engine.on_checkpoint_save():
            return  # torn: the save never becomes visible
        self._inner.save(step, state, metrics=metrics)

    def restore_latest(self, abstract_state):
        if (self._engine.on_checkpoint_restore()
                and corrupt_newest_checkpoint(self._inner.directory)
                is not None):
            self._engine.note_ckpt_corruption_landed()
        return self._inner.restore_latest(abstract_state)

    def __getattr__(self, name: str):
        return getattr(self._inner, name)
