"""T5X-style partitioner — logical axis rules OWN the sharding.

Before this module, sharding lived in two ad-hoc places: a largest-dim
FSDP heuristic (`sharding.fsdp_param_pspec`) and per-model regex→
PartitionSpec tables (`PARTITION_RULES`). Both keep working — they are
now the top and bottom tiers of ONE derivation the partitioner owns:

  1. explicit path rules   (regex → PartitionSpec; the model tables)
  2. logical axis rules    (path → logical dim names → mesh axes)
  3. FSDP heuristic        (largest divisible dim over `fsdp`)

The logical tier is the T5X shape: a param path maps to per-dimension
LOGICAL names (``("embed", "heads")`` for an attention projection), and a
separate rule list maps logical names to MESH axes (``("embed", "fsdp")``,
``("heads", "tensor")``). Changing how a model family shards is then one
rule edit, not N regex rows — and the same logical names place per-stage
gangs in the MPMD pipeline work (ROADMAP item 1).

A named dim that does not divide its mesh-axis product is REPLICATED
(that dim drops to None) instead of discarding the whole rule — the
spec-fits-mesh fallback the tiny-mesh tests pin. The legacy
`sharding.state_pspec` wrapper keeps its historical all-or-nothing rule
matching for existing callers.

The partitioner also owns two step-level contracts the Trainer consumes:

  - ``constrain_grads``: per-rule ``with_sharding_constraint`` on the
    gradient tree, so XLA's scheduler can start each gradient's
    reduce-scatter/all-reduce the moment the layer's backward produces
    it — overlapping collectives with the remaining backward instead of
    serializing one big all-reduce after it (1909.09756's first MFU
    front; gated by the `grad_overlap` cpu-proxy workload).
  - ``deterministic_rng``: partitionable threefry scoped around state
    init and step tracing. The legacy (jax<=0.4.x default) threefry
    path produces DIFFERENT random bits when XLA partitions the
    generator — an FSDP-sharded init diverged from the single-device
    init by ~0.26 abs on a lecun_normal kernel, the root cause of the
    long-standing fsdp-vs-single numerics failures. Under the
    partitioner every layout draws identical bits.
"""

from __future__ import annotations

import re
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_PIPELINE,
    MeshConfig,
    build_mesh,
    build_multislice_mesh,
)

#: params with fewer elements than this replicate under the heuristic
#: (sharding a 128-float bias wastes a collective)
DEFAULT_MIN_SIZE = 2**12

#: accepted spellings for mesh axes in logical rules — "tensor" is the
#: T5X/Megatron name for what our mesh calls `model`
AXIS_ALIASES = {"tensor": AXIS_MODEL}

#: logical name -> mesh axis (str | tuple | None). First match wins,
#: T5X semantics; None pins the dim replicated.
LogicalAxisRules = Sequence[tuple[str, Any]]

#: path regex -> per-dimension logical names. First match wins; a name of
#: None replicates that dim regardless of the axis rules.
PathLogicalRules = Sequence[tuple[str, tuple]]

#: path regex -> PartitionSpec (the legacy model PARTITION_RULES shape)
PathSpecRules = Sequence[tuple[str, P]]

#: The default logical vocabulary. `embed` rides fsdp (ZeRO-3 weight
#: sharding), the matmul-wide dims (`heads`/`mlp`/`vocab`) ride tensor
#: parallelism, `expert` rides expert parallelism, `length` context
#: parallelism; bookkeeping dims (`kv`, `stack`, `norm`, `pos`) replicate.
DEFAULT_LOGICAL_AXIS_RULES: tuple[tuple[str, Any], ...] = (
    ("batch", (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)),
    ("embed", AXIS_FSDP),
    ("heads", "tensor"),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("expert", AXIS_EXPERT),
    ("length", AXIS_CONTEXT),
    ("stage", AXIS_PIPELINE),
    ("kv", None),
    ("stack", None),
    ("norm", None),
    ("pos", None),
)

#: Param-path → logical names for the in-tree transformer families
#: (models/gpt.py, models/bert.py, parallel/moe.py naming). Derives the
#: SAME PartitionSpecs the hand-written PARTITION_RULES tables pin —
#: tests/test_partitioner.py proves the round trip on real param trees.
DEFAULT_PATH_LOGICAL_RULES: tuple[tuple[str, tuple], ...] = (
    # attention projections exist in both shapes: DenseGeneral's
    # (embed, heads, head_dim) rank-3 form (the in-tree models) and the
    # fused rank-2 form — rule lookup is RANK-AWARE (first pattern match
    # whose arity equals the param's rank wins)
    (r"(query|key|value)/kernel$", ("embed", "heads", "kv")),
    (r"(query|key|value)/kernel$", ("embed", "heads")),
    (r"attn_out/kernel$", ("heads", "kv", "embed")),
    (r"attn_out/kernel$", ("heads", "embed")),
    (r"(mlp_up|mlp_gate)/kernel$", ("embed", "mlp")),
    (r"mlp_down/kernel$", ("mlp", "embed")),
    (r"token_embed/embedding$", ("vocab", "embed")),
    (r"(position_embed|type_embed)/embedding$", ("pos", "embed")),
    (r"lm_head/kernel$", ("embed", "vocab")),
    (r"(pooler|mlm_dense)/kernel$", ("embed", "mlp")),
    (r"moe/(w_up|w_gate)$", ("expert", "embed", "mlp")),
    (r"moe/(b_up|b_gate)$", ("expert", "mlp")),
    (r"moe/w_down$", ("expert", "mlp", "embed")),
    (r"moe/b_down$", ("expert", "embed")),
)


def heuristic_pspec(shape: tuple[int, ...], fsdp_size: int,
                    min_size: int = DEFAULT_MIN_SIZE) -> P:
    """The FSDP fallback: shard the largest dim divisible by fsdp_size;
    tiny params replicate. (Moved here from parallel/sharding.py, which
    now delegates — the heuristic is the partitioner's bottom tier.)"""
    if fsdp_size <= 1 or int(np.prod(shape)) < min_size:
        return P()
    candidates = [i for i, d in enumerate(shape) if d % fsdp_size == 0]
    if not candidates:
        return P()
    dim = max(candidates, key=lambda i: shape[i])
    spec: list[Any] = [None] * len(shape)
    spec[dim] = AXIS_FSDP
    return P(*spec)


def spec_fits(spec: P, shape: tuple[int, ...], mesh: Mesh) -> bool:
    """All-or-nothing divisibility check (the legacy state_pspec rule
    contract): rank must not exceed the shape's and every named dim must
    divide its mesh-axis product."""
    if len(spec) > len(shape):
        return False
    for dim, axes in enumerate(spec):
        if axes is None:
            continue
        axes = axes if isinstance(axes, tuple) else (axes,)
        size = int(np.prod([mesh.shape[a] for a in axes]))
        if shape[dim] % size != 0:
            return False
    return True


def fit_spec(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Per-dimension spec-fits-mesh fallback: a named dim whose size does
    not divide its mesh-axis product REPLICATES (drops to None) instead of
    invalidating the whole rule — a 2-head model on a model=4 mesh keeps
    its embed sharding and merely replicates the heads dim. A spec longer
    than the shape's rank replicates entirely (rule/shape mismatch)."""
    if len(spec) > len(shape):
        return P()
    out: list[Any] = []
    for dim, axes in enumerate(spec):
        if axes is None:
            out.append(None)
            continue
        taxes = axes if isinstance(axes, tuple) else (axes,)
        size = int(np.prod([mesh.shape[a] for a in taxes]))
        out.append(axes if size and shape[dim] % size == 0 else None)
    return P(*out)


def resolve_pspec(path_str: str, shape: tuple[int, ...], mesh: Mesh,
                  rules: PathSpecRules | None,
                  min_size: int = DEFAULT_MIN_SIZE) -> P:
    """The legacy derivation (`sharding.state_pspec` delegates here):
    explicit path rules with all-or-nothing fit, then the heuristic."""
    if len(shape) == 0:
        return P()
    if rules:
        for pattern, spec in rules:
            if re.search(pattern, path_str) and spec_fits(spec, shape, mesh):
                return spec
    return heuristic_pspec(shape, mesh.shape[AXIS_FSDP], min_size)


def path_str_of(path) -> str:
    """'/'-joined tree path (DictKey/GetAttr/SequenceKey tolerant)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


# --------------------------------------------------------- comm accounting

#: process-global gradient-communication ledger (observability renders it
#: as kftpu_train_comm_* — zero-valued on an idle process, so the golden
#: exposition pins a stable surface). comm_seconds counts host-visible
#: time spent blocked on gradient collectives that did NOT overlap
#: compute; overlap_ratio is the latest overlapped/serialized step-time
#: ratio measured by the grad_overlap machinery (1.0 = no overlap won).
_COMM_METRICS = {
    "comm_seconds_total": 0.0,
    "overlap_measurements_total": 0,
}
_LAST_OVERLAP_RATIO = 0.0


def record_comm(seconds: float, overlap_ratio: float | None = None) -> None:
    """Account gradient-communication wall time (and optionally a new
    overlap-ratio measurement) into the process-global ledger."""
    global _LAST_OVERLAP_RATIO
    _COMM_METRICS["comm_seconds_total"] += float(seconds)
    if overlap_ratio is not None:
        _COMM_METRICS["overlap_measurements_total"] += 1
        _LAST_OVERLAP_RATIO = float(overlap_ratio)


def comm_metrics_snapshot() -> dict:
    return dict(_COMM_METRICS, overlap_ratio=_LAST_OVERLAP_RATIO)


def reset_comm_metrics() -> None:
    """Test hook: zero the ledger (the golden-exposition test pins the
    zero-valued families)."""
    global _LAST_OVERLAP_RATIO
    _COMM_METRICS["comm_seconds_total"] = 0.0
    _COMM_METRICS["overlap_measurements_total"] = 0
    _LAST_OVERLAP_RATIO = 0.0


@dataclass
class Partitioner:
    """Derives every PartitionSpec the trainer needs from one rule set.

    mesh construction is folded in: pass a ready `mesh`, or a
    `mesh_config` (+ `num_slices` > 1 for the hybrid DCN×ICI multislice
    mesh, with `build_multislice_mesh`'s no-ICI-axis-across-DCN guard).

    Derivation order for a param/state leaf (first hit wins):
      1. `path_specs`  — explicit regex → PartitionSpec (model
         PARTITION_RULES); per-dim fitted to the mesh (non-dividing dims
         replicate).
      2. `path_logical` + `logical_rules` — path → logical dim names →
         mesh axes; unknown logical names replicate loudly only under
         `strict`, silently otherwise (the T5X default).
      3. FSDP heuristic (largest divisible dim over `fsdp`).
    """

    mesh: Mesh | None = None
    mesh_config: MeshConfig | None = None
    num_slices: int = 1
    path_specs: PathSpecRules | None = None
    path_logical: PathLogicalRules = DEFAULT_PATH_LOGICAL_RULES
    logical_rules: LogicalAxisRules = DEFAULT_LOGICAL_AXIS_RULES
    min_size: int = DEFAULT_MIN_SIZE
    strict: bool = False
    _logical_map: dict = field(init=False, repr=False, default_factory=dict)

    def __post_init__(self):
        if self.mesh is None:
            cfg = self.mesh_config or MeshConfig()
            self.mesh = (build_multislice_mesh(self.num_slices, cfg)
                         if self.num_slices > 1 else build_mesh(cfg))
        # first-match-wins: build the lookup once, earlier rules shadow
        for name, axes in self.logical_rules:
            if name not in self._logical_map:
                self._logical_map[name] = self._canon(axes)

    @staticmethod
    def _canon(axes):
        if axes is None:
            return None
        if isinstance(axes, (tuple, list)):
            return tuple(AXIS_ALIASES.get(a, a) for a in axes)
        return AXIS_ALIASES.get(axes, axes)

    # ------------------------------------------------------------ derivation

    def mesh_axes_for(self, logical: str):
        """Mesh axis (or tuple, or None) for one logical dim name."""
        if logical in self._logical_map:
            return self._logical_map[logical]
        if self.strict:
            raise ValueError(
                f"no logical axis rule for {logical!r} "
                f"(rules: {[n for n, _ in self.logical_rules]})")
        return None

    def logical_to_spec(self, logical_axes: Sequence[str | None],
                        shape: tuple[int, ...]) -> P:
        """Logical dim names → fitted PartitionSpec over this mesh."""
        spec = P(*(None if name is None else self.mesh_axes_for(name)
                   for name in logical_axes))
        return fit_spec(spec, shape, self.mesh)

    def logical_axes_for_path(self, path_str: str,
                              rank: int | None = None) -> tuple | None:
        """First matching rule; with `rank`, the first match whose arity
        equals it (the same param name can carry different logical shapes
        — fused vs per-head attention projections)."""
        for pattern, names in self.path_logical:
            if re.search(pattern, path_str) and (
                    rank is None or len(names) == rank):
                return tuple(names)
        return None

    def spec_for(self, path_str: str, shape: tuple[int, ...]) -> P:
        """The full three-tier derivation for one state leaf."""
        if len(shape) == 0:
            return P()
        if self.path_specs:
            for pattern, spec in self.path_specs:
                if re.search(pattern, path_str):
                    return fit_spec(spec, shape, self.mesh)
        logical = self.logical_axes_for_path(path_str, rank=len(shape))
        if logical is not None:
            return self.logical_to_spec(logical, shape)
        return heuristic_pspec(shape, self.mesh.shape[AXIS_FSDP],
                               self.min_size)

    # -------------------------------------------------------- trainer hooks

    def state_shardings(self, state: Any) -> Any:
        """NamedSharding pytree matching `state` (jit in/out_shardings,
        checkpoint restore targets). Rules written against param paths
        also hit the mirrored adam mu/nu trees — the param path is a
        suffix of the optimizer-state path."""

        def one(path, leaf):
            spec = self.spec_for(path_str_of(path), np.shape(leaf))
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map_with_path(one, state)

    def grad_specs(self, params: Any) -> Any:
        """PartitionSpec tree for a gradient pytree: gradients share the
        parameter layout (that is what makes the per-rule constraint a
        reduce-scatter XLA can start early)."""
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(path_str_of(path),
                                             np.shape(leaf)),
            params,
        )

    def constrain_grads(self, grads: Any) -> Any:
        """Per-rule `with_sharding_constraint` over the gradient tree —
        the comm/compute-overlap hook. Pinning each gradient to its
        param's layout right where backward produces it lets XLA's
        latency-hiding scheduler overlap every gradient's collective with
        the REST of the backward pass, instead of fusing one serialized
        all-reduce after it (docs/partitioner.md "Overlap mechanics")."""

        def one(path, g):
            spec = self.spec_for(path_str_of(path), np.shape(g))
            if not any(a is not None for a in tuple(spec)):
                # fully-replicated grad: a constraint would only add a
                # no-op custom-call per leaf to every compiled step (the
                # common pure-data-parallel case) — nothing to overlap
                return g
            return jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map_with_path(one, grads)

    # ------------------------------------------------------------- numerics

    @contextmanager
    def deterministic_rng(self):
        """Partitionable threefry for everything traced inside: random
        draws become layout-invariant — an FSDP/TP-sharded init produces
        bit-identical params to the single-device init (the fsdp-vs-
        single numerics fix; see module docstring). Scoped, not global:
        the legacy generator's values are pinned by seeded tests
        elsewhere in the repo."""
        with jax.threefry_partitionable(True):
            yield

    # ------------------------------------------------------------ cache key

    def key_fields(self) -> dict:
        """Everything about this partitioner that changes the compiled
        step program, in stable string form — folded into the trainer's
        executable content key so the restart-warm compile cache can
        never serve a binary built under different sharding rules."""
        def spec_s(spec):
            return repr(tuple(spec))

        return {
            "mesh": tuple(sorted(self.mesh.shape.items())),
            "num_slices": self.num_slices,
            "path_specs": tuple(
                (p, spec_s(s)) for p, s in (self.path_specs or ())),
            "path_logical": tuple(
                (p, tuple(n)) for p, n in self.path_logical),
            # key the EFFECTIVE first-match-wins map, None entries
            # included: a rule pinning a logical dim replicated must move
            # the key exactly like one sharding it (dropping Nones — or
            # keying the raw ordered list — would let two partitioners
            # with different effective sharding share a cached binary)
            "logical_rules": tuple(sorted(
                (k, "+".join(v) if isinstance(v, tuple) else str(v))
                for k, v in self._logical_map.items())),
            "min_size": self.min_size,
        }
