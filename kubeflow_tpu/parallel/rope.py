"""Rotary position embedding — shared by the GPT family and the
context-parallel attention paths (which must rotate by GLOBAL position
inside their shard regions; see ring/ulysses in ring_attention.py)."""

from __future__ import annotations

import jax.numpy as jnp


def apply_rope(x, pos, theta: float = 10000.0):
    """Rotary position embedding (half-split convention): rotate each
    head-dim pair by pos * theta^(-2i/d). x: (B, L, H, D); pos: (L,)
    shared across the batch, or (B, L) per-row (continuous-batching
    decode, where in-flight rows sit at different depths)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    ang = pos.astype(jnp.float32)[..., None] * freqs  # (..., L, D/2)
    if ang.ndim == 2:                                 # shared (L, D/2)
        ang = ang[None]
    cos = jnp.cos(ang)[:, :, None, :]                 # (B|1, L, 1, D/2)
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)
