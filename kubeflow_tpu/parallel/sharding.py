"""Sharding rules: batch + parameter placement over the mesh.

DP:   batch sharded over (data, fsdp); params replicated.
FSDP: params additionally sharded over `fsdp` on their largest divisible
      axis (ZeRO-3 analogue — XLA all-gathers weights per layer and
      reduce-scatters grads; optimizer state inherits the param sharding
      through optax's tree structure).
TP:   models annotate logical axes (flax partitioning) mapped via RULES;
      handled in kubeflow_tpu/models with nn.with_partitioning.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP


def batch_pspec() -> P:
    """Leading (batch) dim split over data×fsdp; rest replicated."""
    return P((AXIS_DATA, AXIS_FSDP))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec())


def fsdp_param_pspec(shape: tuple[int, ...], fsdp_size: int, min_size: int = 2**12) -> P:
    """Shard the largest dim divisible by fsdp_size; tiny params replicate.

    min_size gate: sharding a 128-float bias wastes a collective; only params
    with >= min_size elements are sharded (same heuristic big FSDP impls use).
    """
    if fsdp_size <= 1 or int(np.prod(shape)) < min_size:
        return P()
    # prefer the largest divisible dim (most even split, fewest pad bytes)
    candidates = [i for i, d in enumerate(shape) if d % fsdp_size == 0]
    if not candidates:
        return P()
    dim = max(candidates, key=lambda i: shape[i])
    spec: list[Any] = [None] * len(shape)
    spec[dim] = AXIS_FSDP
    return P(*spec)


def param_shardings(params: Any, mesh: Mesh, min_size: int = 2**12) -> Any:
    """NamedSharding tree for a param pytree under the mesh's fsdp axis."""
    fsdp_size = mesh.shape[AXIS_FSDP]

    def one(leaf):
        return NamedSharding(mesh, fsdp_param_pspec(np.shape(leaf), fsdp_size, min_size))

    return jax.tree.map(one, params)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    """Place a host batch onto the mesh, split along the data axes."""
    s = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), batch)


def shard_state(state: Any, mesh: Mesh) -> Any:
    """Place a TrainState: params/opt_state FSDP-sharded, scalars replicated."""

    def one(leaf):
        if np.ndim(leaf) == 0:
            return jax.device_put(leaf, replicated(mesh))
        fsdp_size = mesh.shape[AXIS_FSDP]
        ns = NamedSharding(mesh, fsdp_param_pspec(np.shape(leaf), fsdp_size))
        return jax.device_put(leaf, ns)

    return jax.tree.map(one, state)
