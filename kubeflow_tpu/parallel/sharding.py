"""Sharding rules: batch + parameter placement over the mesh.

DP:   batch sharded over (data, fsdp); params replicated.
FSDP: params additionally sharded over `fsdp` on their largest divisible
      axis (ZeRO-3 analogue — XLA all-gathers weights per layer and
      reduce-scatters grads; optimizer state inherits the param sharding
      through optax's tree structure).
TP:   models publish PARTITION_RULES — (path_regex, PartitionSpec) pairs
      matched against the '/'-joined param path (t5x-style). Rules win over
      the FSDP heuristic; unmatched params fall back to it. The same rules
      apply to optimizer state because adam's mu/nu trees embed the param
      path as a suffix of their own tree path.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_DATA, AXIS_EXPERT, AXIS_FSDP

Rules = Sequence[tuple[str, P]]

# every data-like mesh axis the batch dim is split over; expert parallelism
# subdivides data parallelism (parallel/moe.py), so `expert` rides along
BATCH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)


def batch_pspec() -> P:
    """Leading (batch) dim split over the data-like axes; rest replicated."""
    return P(BATCH_AXES)


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a (k, B, ...) stacked batch chunk: scan dim replicated,
    batch dim split over the data-like axes (Trainer.train_chunk)."""
    return NamedSharding(mesh, P(None, BATCH_AXES))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec())


def fsdp_param_pspec(shape: tuple[int, ...], fsdp_size: int, min_size: int = 2**12) -> P:
    """Shard the largest dim divisible by fsdp_size; tiny params replicate.

    Thin wrapper: the heuristic now lives in parallel/partitioner.py as
    the derivation's bottom tier (min_size gate unchanged — sharding a
    128-float bias wastes a collective)."""
    from kubeflow_tpu.parallel.partitioner import heuristic_pspec

    return heuristic_pspec(shape, fsdp_size, min_size)


def param_shardings(params: Any, mesh: Mesh, min_size: int = 2**12) -> Any:
    """NamedSharding tree for a param pytree under the mesh's fsdp axis."""
    fsdp_size = mesh.shape[AXIS_FSDP]

    def one(leaf):
        return NamedSharding(mesh, fsdp_param_pspec(np.shape(leaf), fsdp_size, min_size))

    return jax.tree.map(one, params)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def put_global(x: Any, sharding: NamedSharding) -> Any:
    """Place one host array under a sharding, single- or multi-process.

    Multi-process convention: every process holds the full host value (data
    pipelines are seed-deterministic), and each device picks its slice via
    make_array_from_callback — the multi-host-safe construction (device_put
    cannot target non-addressable devices).
    """
    if isinstance(x, jax.Array) and not isinstance(x, np.ndarray):
        # Already placed (e.g. by prefetch_to_device) — pass through; a
        # multi-process global array cannot be np.asarray'd. The pass-through
        # requires an actual NamedSharding, not mere placement equivalence: a
        # SingleDeviceSharding is "equivalent" to a replicated NamedSharding
        # on a 1-device mesh, but jit treats them as different input
        # specializations, so passing it through makes every Trainer pay a
        # second (on TPU: remote, ~tens of seconds) train-step compile when
        # the first step's NamedSharding outputs feed back in.
        if isinstance(x.sharding, NamedSharding) and x.sharding.is_equivalent_to(
            sharding, x.ndim
        ):
            return x
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        if not x.is_fully_addressable:
            raise ValueError(
                f"cannot reshard a global array from {x.sharding} to "
                f"{sharding} outside jit in multi-process mode"
            )
        # process-local array: fall through to the host-copy construction
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def put_process_local(x: Any, sharding: NamedSharding) -> Any:
    """Assemble a global array from PER-PROCESS shards: each process holds
    only its own rows (disjoint data loading — train/data.py
    load_dataset_shards), and jax stitches the global batch across hosts.
    The complement of put_global's replicated convention; single-process it
    degenerates to a plain placement."""
    if isinstance(x, jax.Array) and not isinstance(x, np.ndarray):
        if isinstance(x.sharding, NamedSharding) and x.sharding.is_equivalent_to(
            sharding, x.ndim
        ):
            return x
    if jax.process_count() == 1:
        return jax.device_put(np.asarray(x), sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))


def shard_batch(batch: Any, mesh: Mesh, process_local: bool = False) -> Any:
    """Place a host batch onto the mesh, split along the data axes.

    process_local=True treats each process's arrays as ITS shard of the
    global batch (disjoint per-host data pipelines); the default expects
    every process to hold the identical full batch."""
    s = batch_sharding(mesh)
    place = put_process_local if process_local else put_global
    return jax.tree.map(lambda x: place(x, s), batch)


def _path_str(path) -> str:
    """Thin wrapper: partitioner.path_str_of is the one stringifier, so
    legacy and partitioner-side rule matching can never see different
    path strings for the same leaf."""
    from kubeflow_tpu.parallel.partitioner import path_str_of

    return path_str_of(path)


def _spec_fits(spec: P, shape: tuple[int, ...], mesh: Mesh) -> bool:
    """A rule spec applies only if rank matches and every named dim divides.
    Thin wrapper over partitioner.spec_fits (the all-or-nothing legacy
    contract; the partitioner's own tier fits per-dim instead)."""
    from kubeflow_tpu.parallel.partitioner import spec_fits

    return spec_fits(spec, shape, mesh)


def state_pspec(
    path_str: str, shape: tuple[int, ...], mesh: Mesh, rules: Rules | None
) -> P:
    """PartitionSpec for one state leaf: rules first, FSDP heuristic second.
    Thin wrapper — parallel/partitioner.resolve_pspec is the one owner of
    this derivation; existing callers keep this entry point."""
    from kubeflow_tpu.parallel.partitioner import resolve_pspec

    return resolve_pspec(path_str, shape, mesh, rules)


def shard_state(state: Any, mesh: Mesh, rules: Rules | None = None) -> Any:
    """Place a TrainState: params/opt_state rule- or FSDP-sharded, scalars
    replicated. Paths are matched on the full state path, so rules written
    against param paths also hit the mirrored adam mu/nu trees."""
    return jax.tree.map(put_global, state, state_shardings(state, mesh, rules))


def state_shardings(state: Any, mesh: Mesh, rules: Rules | None = None) -> Any:
    """NamedSharding pytree matching `state` (for jit out_shardings/ckpt)."""

    def one(path, leaf):
        spec = state_pspec(_path_str(path), np.shape(leaf), mesh, rules)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, state)
