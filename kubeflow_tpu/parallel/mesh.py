"""Device mesh construction — the one mechanism under every strategy.

The canonical axis vocabulary (SURVEY.md §2.2 table):
  data     pure data parallel (gradient allreduce)
  fsdp     data parallel with sharded params/optimizer (ZeRO-3 analogue)
  model    tensor parallel (matmul sharding over ICI)
  context  sequence/context parallel (ring attention KV rotation)
  pipeline pipeline stages (microbatch loop over ppermute)
  expert   MoE expert parallel (all-to-all dispatch)

Mesh axes are ordered fastest-varying-last onto the physical topology; ICI
bandwidth favors putting `model`/`context` on the innermost (intra-slice)
dimension and `data` on the outermost (inter-slice DCN) dimension — the
scaling-book recipe.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

from kubeflow_tpu.utils import compat

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_MODEL = "model"
AXIS_CONTEXT = "context"
AXIS_PIPELINE = "pipeline"
AXIS_EXPERT = "expert"

# Outer-to-inner canonical order: data-like axes ride DCN, model-like ride ICI.
CANONICAL_ORDER = [AXIS_DATA, AXIS_FSDP, AXIS_PIPELINE, AXIS_EXPERT, AXIS_CONTEXT, AXIS_MODEL]


@dataclass
class MeshConfig:
    """Sizes per axis; -1 on at most one axis means 'all remaining devices'."""

    data: int = -1
    fsdp: int = 1
    model: int = 1
    context: int = 1
    pipeline: int = 1
    expert: int = 1

    def sizes(self) -> dict[str, int]:
        return {
            AXIS_DATA: self.data,
            AXIS_FSDP: self.fsdp,
            AXIS_PIPELINE: self.pipeline,
            AXIS_EXPERT: self.expert,
            AXIS_CONTEXT: self.context,
            AXIS_MODEL: self.model,
        }


def build_mesh(
    config: MeshConfig | None = None, devices: list | None = None
) -> Mesh:
    """Build a Mesh over `devices` (default: all local devices).

    Axes of size 1 are kept in the mesh so sharding specs can always name
    them — XLA erases trivial axes at compile time, so this costs nothing.
    """
    config = config or MeshConfig()
    if devices is None:
        devices = jax.devices()
    n = len(devices)

    sizes = config.sizes()
    wild = [a for a, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one axis may be -1, got {wild}")
    fixed = math.prod(s for s in sizes.values() if s != -1)
    if wild:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {fixed}"
            )
        sizes[wild[0]] = n // fixed
    elif fixed != n:
        raise ValueError(f"axis sizes {sizes} product {fixed} != {n} devices")

    axis_names = tuple(CANONICAL_ORDER)
    shape = tuple(sizes[a] for a in axis_names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def single_device_mesh() -> Mesh:
    """1-device mesh with the full axis vocabulary (all sizes 1 except data)."""
    return build_mesh(MeshConfig(), jax.devices()[:1])


def build_multislice_mesh(
    num_slices: int, config: MeshConfig | None = None, devices: list | None = None
) -> Mesh:
    """Mesh for a multislice (DCN/megascale) job.

    Devices arrive slice-major from jax.devices() (processes are ordered by
    id and slices are contiguous process ranges — envcontract.jax_env), so
    with the canonical outer-to-inner axis order the data-like axes span
    slices (DCN) while model-like axes stay inside a slice (ICI) — the
    scaling-book placement. Validates that the outermost non-trivial axis is
    a multiple of num_slices so no ICI-class axis straddles a DCN boundary.
    """
    config = config or MeshConfig()
    mesh = build_mesh(config, devices)
    # only the data-like outer axes may straddle the DCN boundary; model/
    # context/expert/pipeline collectives must stay inside one slice's ICI
    dcn = mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
    if dcn % num_slices != 0:
        raise ValueError(
            f"mesh {dict(mesh.shape)}: data×fsdp = {dcn} is not a multiple "
            f"of num_slices {num_slices}; an ICI-class axis would straddle "
            f"the DCN slice boundary"
        )
    return mesh


# ---------------------------------------------------------------- manual region

# Trace-time marker: set while a stage body is being traced INSIDE an
# already-manual shard_map region (gpipe's pipeline ring). Collective
# constructs that normally open their OWN shard_map (ring/ulysses
# attention, MoE dispatch) consult it and fall back to their
# auto-partitioned formulation instead of nesting — reverse-mode AD
# through a nested shard_map inside a manual region produces WRONG
# cotangents in current JAX (forward exact, gradients corrupted; found
# by the r5 real-dim composed execution test: finite loss, NaN/exploding
# grad-norm growing geometrically with layers-per-stage). The
# auto-partitioned bodies compute identical math and let the XLA
# partitioner insert the context/expert collectives.
import contextvars as _contextvars

_IN_MANUAL_REGION = _contextvars.ContextVar("kft_in_manual_region",
                                            default=False)


class manual_region:
    """Context manager marking 'tracing inside a manual shard_map body'.

    Explicit marker (gpipe sets it around stage bodies); in_manual_region
    ALSO auto-detects via the abstract mesh's axis types, so a future
    manual construct that forgets the marker still routes its inner
    collectives safely."""

    def __enter__(self):
        self._tok = _IN_MANUAL_REGION.set(True)
        return self

    def __exit__(self, *exc):
        _IN_MANUAL_REGION.reset(self._tok)
        return False


def in_manual_region() -> bool:
    """True while tracing inside any manual shard_map region — via the
    explicit marker OR the ambient abstract mesh's axis types (inside a
    shard_map body the bound axes report Manual), so detection does not
    depend on every manual-region author remembering the marker."""
    if _IN_MANUAL_REGION.get():
        return True
    mesh = compat.get_abstract_mesh()
    if mesh.empty:
        return False
    try:
        manual = jax.sharding.AxisType.Manual
        return any(t == manual for t in mesh.axis_types)
    except AttributeError:  # older jax without axis_types/AxisType
        return False
