"""Parallelism layer: mesh building, shardings, pipeline/sequence parallel.

Replaces the reference's strategy zoo (DDP/FSDP/Megatron-TP/DeepSpeed-PP/
MoE-EP over NCCL — SURVEY.md §2.2) with the TPU-native single mechanism:
a `jax.sharding.Mesh` with named axes and NamedSharding/shard_map
annotations; XLA inserts the ICI/DCN collectives.
"""

from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh, AXIS_DATA, AXIS_FSDP, AXIS_MODEL, AXIS_CONTEXT, AXIS_EXPERT, AXIS_PIPELINE
from kubeflow_tpu.parallel.partitioner import Partitioner
from kubeflow_tpu.parallel import ring_attention

__all__ = [
    "MeshConfig",
    "Partitioner",
    "build_mesh",
    "ring_attention",
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_MODEL",
    "AXIS_CONTEXT",
    "AXIS_EXPERT",
    "AXIS_PIPELINE",
]
