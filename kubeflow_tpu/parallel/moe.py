"""Expert parallelism — mixture-of-experts dispatch over the `expert` axis.

Reference parity: the reference has no in-platform MoE (DeepSpeed-MoE user
images supply it — SURVEY.md §2.2 "Expert parallel (EP/MoE)"); here it is a
first-class construct, TPU-first:

  - EP is a subdivision of data parallelism (the Megatron/DeepSpeed-EP
    layout): the batch is sharded over (data, fsdp, expert) and expert
    weights over `expert`, so the token exchange is a true all-to-all that
    rides ICI inside the expert group.
  - The dispatch is a *partial-manual* shard_map over the data-like axes
    (data, fsdp, expert): `lax.all_to_all` is explicit (the one collective
    that matters) and routing is shard-local, while model/context shardings
    inside the body stay automatic — XLA still inserts the TP psums for the
    expert matmuls. (With `global_dispatch=True` only `expert` is manual
    and fsdp stays auto inside the body.)
  - Top-k softmax router (f32), capacity-factor slotting via cumsum
    priority, dropped tokens pass through with zero combine weight (the
    residual connection carries them), Switch-style load-balance aux loss.

Capacity is LOCAL per (data, fsdp, expert) shard: C = ceil(k * t_local * cf
/ E) where t_local is the shard's own token count. The dispatch shard_map is
manual over the data-like axes too, so the slot-assignment cumsum never
spans data shards — no collective scan inside the router (the Switch/
DeepSpeed-EP local-dispatch recipe; the earlier GShard-style global cumsum
ran a cross-shard scan per MoE layer). `global_dispatch=True` restores the
old behavior (global capacity pool, cross-shard cumsum) for comparison.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.utils import compat
from kubeflow_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    in_manual_region,
)

# Param-path regex -> PartitionSpec for MoE params (merged into model rules).
MOE_PARTITION_RULES: list[tuple[str, P]] = [
    (r"moe/(w_up|w_gate)$", P(AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL)),
    (r"moe/(b_up|b_gate)$", P(AXIS_EXPERT, AXIS_MODEL)),
    (r"moe/w_down$", P(AXIS_EXPERT, AXIS_MODEL, AXIS_FSDP)),
    (r"moe/b_down$", P(AXIS_EXPERT, AXIS_FSDP)),
]


def _route(logits: jax.Array, top_k: int, capacity: int):
    """Shared routing math for both the sharded and dense paths.

    logits: (T, E) f32. Returns (combine (T, E, C), dispatch (T, E, C) bool,
    aux_loss scalar). Tokens beyond an expert's capacity are dropped (zero
    combine weight); the caller's residual connection carries them through.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gates, idx = jax.lax.top_k(probs, top_k)              # (T, K)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    onehot = jax.nn.one_hot(idx, e, dtype=logits.dtype)   # (T, K, E)

    # Switch-transformer load balance: E * Σ_e fraction_of_tokens_e · mean_prob_e
    frac = onehot[:, 0].mean(axis=0)                      # top-1 assignment share
    aux = e * jnp.sum(frac * probs.mean(axis=0))

    # slot position: cumsum priority in (token-major, then k) order
    flat = onehot.reshape(t * top_k, e)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat)     # (T*K, E)
    pos = (pos_in_expert * flat).sum(-1).reshape(t, top_k).astype(jnp.int32)
    keep = (pos < capacity).astype(logits.dtype)
    slot = jax.nn.one_hot(pos, capacity, dtype=logits.dtype)  # (T, K, C)

    combine = jnp.einsum("tke,tkc->tec", onehot * (gates * keep)[..., None], slot)
    dispatch = jnp.einsum("tke,tkc->tec", onehot * keep[..., None], slot)
    return combine, dispatch, aux


class MoeMlp(nn.Module):
    """Drop-in MoE replacement for a transformer MLP block.

    __call__(x) with x: (B, L, H) returns (B, L, H); the load-balance aux
    loss is sown into the 'losses' collection (the Trainer adds every
    'losses' leaf to the objective).
    """

    hidden_size: int
    mlp_dim: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32
    # True restores the round-2-initial GShard-style dispatch: one capacity
    # pool over the whole (data x fsdp x expert) batch, slot cumsum as a
    # cross-shard collective scan. Default is local dispatch (see module
    # docstring).
    global_dispatch: bool = False
    # Expert FFN shape: "gelu" (GShard/BERT default, biased) or "swiglu"
    # (Mixtral: silu(gate)·up per expert); use_bias=False drops every
    # expert bias. Defaults keep the historical parameter tree byte-
    # identical (checkpoint-compatible).
    activation: str = "gelu"
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, dropless: bool = False) -> jax.Array:
        h, f, e = self.hidden_size, self.mlp_dim, self.num_experts
        if self.activation not in ("gelu", "swiglu"):
            raise ValueError(
                f"activation {self.activation!r} is not gelu|swiglu")
        router = self.param(
            "router", nn.initializers.normal(stddev=0.02), (h, e), jnp.float32
        )
        init = nn.initializers.lecun_normal()
        zeros = nn.initializers.zeros
        swiglu = self.activation == "swiglu"
        # weights live in ONE dict pytree so every dispatch path (dropless
        # / local shard_map / global) threads the same set, whatever the
        # activation/bias combination. Creation ORDER preserves the
        # historical sequence (w_up, b_up, w_down, b_down) with new swiglu
        # params strictly after — flax folds a per-scope call counter into
        # each param's init RNG, so reordering would silently change
        # fresh-init values for the default config.
        ws = {"w_up": self.param("w_up", init, (e, h, f))}
        if self.use_bias:
            ws["b_up"] = self.param("b_up", zeros, (e, f))
        ws["w_down"] = self.param("w_down", init, (e, f, h))
        if self.use_bias:
            ws["b_down"] = self.param("b_down", zeros, (e, h))
        if swiglu:
            ws["w_gate"] = self.param("w_gate", init, (e, h, f))
            if self.use_bias:
                ws["b_gate"] = self.param("b_gate", zeros, (e, f))

        def ffn(xin, ws):
            """Per-expert FFN: xin (E, C, H) against stacked weights."""
            up = jnp.einsum("ech,ehf->ecf", xin,
                            ws["w_up"].astype(xin.dtype))
            if "b_up" in ws:
                up = up + ws["b_up"].astype(xin.dtype)[:, None, :]
            if swiglu:
                gate = jnp.einsum("ech,ehf->ecf", xin,
                                  ws["w_gate"].astype(xin.dtype))
                if "b_gate" in ws:
                    gate = gate + ws["b_gate"].astype(xin.dtype)[:, None, :]
                act = nn.silu(gate) * up
            else:
                act = nn.gelu(up)
            y = jnp.einsum("ecf,efh->ech", act, ws["w_down"].astype(xin.dtype))
            if "b_down" in ws:
                y = y + ws["b_down"].astype(xin.dtype)[:, None, :]
            return y

        if dropless:
            # DROPLESS routing — the decode path (VERDICT r4 #6). Every
            # token gets its full top-k combine, no capacity, no cumsum:
            # each token's output depends only on ITS hidden state, so
            # rows are independent and continuous batching / speculative
            # verify compose with MoE exactly (capacity dispatch couples
            # rows: the drop pattern depends on batch composition).
            # Cost: every expert runs on every token — at decode widths
            # (1..gamma+1 tokens/row) the weights stream from HBM anyway
            # (bandwidth-bound), so the extra FLOPs ride the same bytes.
            # No aux loss: decode never trains.
            b, l, _ = x.shape
            xt = x.reshape(b * l, h)
            logits = xt.astype(jnp.float32) @ router        # (T, E)
            probs = jax.nn.softmax(logits, axis=-1)
            gates, idx = jax.lax.top_k(probs, self.top_k)
            gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
            weight = (jax.nn.one_hot(idx, e, dtype=jnp.float32)
                      * gates[..., None]).sum(1)            # (T, E)
            down = ffn(jnp.broadcast_to(xt[None], (e, b * l, h)), ws)
            y = jnp.einsum("te,eth->th", weight.astype(xt.dtype), down)
            return y.reshape(b, l, h)

        mesh = compat.get_abstract_mesh()
        ep = 1 if mesh.empty else mesh.shape.get(AXIS_EXPERT, 1)
        if e % ep:
            raise ValueError(f"num_experts {e} not divisible by expert axis {ep}")
        # data-like extents: with local dispatch these axes join the manual
        # region so the router's cumsum stays shard-local. A context-sharded
        # sequence dim joins too — routing is per-token, so context shards
        # are just more local tokens (otherwise the partitioner must gather
        # L at the dispatch boundary, a full-remat reshard under a pipeline
        # ring with sequence parallelism).
        dp = 1 if mesh.empty else mesh.shape.get(AXIS_DATA, 1)
        fs = 1 if mesh.empty else mesh.shape.get(AXIS_FSDP, 1)
        cp = 1 if mesh.empty else mesh.shape.get(AXIS_CONTEXT, 1)

        def moe_body(xb, rw, ws, manual_axes):
            """xb (B_local, L, H); ws: dict of stacked expert weights,
            leading dim E/ep inside the manual region. With local dispatch
            the data axes are manual too, so `t` — and the capacity — are
            per-shard and the cumsum in _route never crosses shards."""
            b, l, _ = xb.shape
            t = b * l
            cap = int(np.ceil(self.top_k * t * self.capacity_factor / e))
            xt = xb.reshape(t, h)
            logits = xt.astype(jnp.float32) @ rw
            combine, dispatch, aux = _route(logits, self.top_k, cap)
            combine = combine.astype(xt.dtype)
            dispatch = dispatch.astype(xt.dtype)
            expert_in = jnp.einsum("tec,th->ech", dispatch, xt)  # (E, C, H)
            # the explicit all-to-all needs AXIS_EXPERT bound as manual;
            # the auto-partitioned path (manual_axes=(), e.g. inside a
            # gpipe stage) lets XLA place the exchange itself
            if ep > 1 and manual_axes:
                # exchange token slots: (E, C, H) -> (E/ep, ep*C, H); each
                # group now holds every shard's slots for ITS experts
                expert_in = jax.lax.all_to_all(
                    expert_in, AXIS_EXPERT, split_axis=0, concat_axis=1, tiled=True
                )
            out = ffn(expert_in, ws)
            if ep > 1 and manual_axes:
                out = jax.lax.all_to_all(
                    out, AXIS_EXPERT, split_axis=1, concat_axis=0, tiled=True
                )
            y = jnp.einsum("tec,ech->th", combine, out)
            reduce_axes = tuple(a for a in manual_axes if mesh.shape.get(a, 1) > 1)
            if reduce_axes:
                aux = jax.lax.pmean(aux, reduce_axes)
            return y.reshape(b, l, h), aux

        local = not self.global_dispatch
        manual: tuple = ()
        # inside a gpipe stage body (in_manual_region): a NESTED
        # shard_map's reverse AD corrupts cotangents in current JAX (see
        # mesh.manual_region and the ring_attention note) — keep
        # manual=() so the dispatch runs auto-partitioned below (global
        # capacity pool; XLA inserts the expert collectives)
        if not mesh.empty and not in_manual_region():
            if local and (ep > 1 or dp > 1 or fs > 1 or cp > 1):
                manual = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)
                if x.shape[0] % (dp * fs * ep) or x.shape[1] % cp:
                    # local dispatch needs the batch dim split across ALL
                    # data-like axes; a batch that only divides the expert
                    # extent keeps the old expert-only manual region (global
                    # capacity pool) instead of failing deep inside shard_map
                    import warnings

                    warnings.warn(
                        f"MoeMlp: batch {x.shape[0]} not divisible by the "
                        f"data-like mesh extent {dp * fs * ep} (or seq "
                        f"{x.shape[1]} by context {cp}); falling back "
                        f"to GLOBAL dispatch (cross-shard routing cumsum, "
                        f"global capacity pool) — pad the batch for local "
                        f"dispatch",
                        stacklevel=2,
                    )
                    manual = (AXIS_EXPERT,) if ep > 1 else ()
                elif cp > 1:
                    # context-sharded tokens are just more local tokens
                    manual = manual + (AXIS_CONTEXT,)
            elif ep > 1:
                manual = (AXIS_EXPERT,)
        if not manual:
            y, aux = moe_body(x, router, ws, ())
        else:
            batch_axes = tuple(a for a in manual if a != AXIS_CONTEXT)
            batch_spec = P(
                batch_axes,
                AXIS_CONTEXT if AXIS_CONTEXT in manual else None,
                None,
            )
            ws_specs = {k: (P(AXIS_EXPERT, None, None) if v.ndim == 3
                            else P(AXIS_EXPERT, None))
                        for k, v in ws.items()}
            y, aux = jax.shard_map(
                partial(moe_body, manual_axes=manual),
                mesh=mesh,
                axis_names=set(manual),
                in_specs=(
                    batch_spec,                   # batch dim carries the manual axes
                    P(None, None),                # router replicated
                    ws_specs,
                ),
                out_specs=(batch_spec, P()),
                check_vma=False,
            )(x, router, ws)
        self.sow("losses", "moe_aux", aux,
                 reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)
        return y
